"""H2OGridSearch — hyperparameter search.

Reference parity: `h2o-algos/src/main/java/hex/grid/GridSearch.java`,
`hex/grid/HyperSpaceWalker.java` (Cartesian + RandomDiscrete strategies,
`search_criteria`: max_models / max_runtime_secs / seed / stopping_*),
`hex/grid/Grid.java` (keyed store of built models) and the client surface
`h2o-py/h2o/grid/grid_search.py` (`H2OGridSearch(model, hyper_params,
search_criteria)`, `get_grid(sort_by, decreasing)`).

Models in a grid are independent → on a pod this is embarrassingly parallel
across hosts; round 1 builds sequentially (each build already uses the full
mesh), which matches the reference's default parallelism=1 sequential walk.
"""

from __future__ import annotations

import itertools
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..frame.frame import Frame


class _RecoveredModel:
    """Checkpointed grid model restored from its artifact: scores via the
    MOJO scorer; metric accessors replay the persisted values so a resumed
    grid's leaderboard includes pre-crash models."""

    def __init__(self, combo, path, metrics):
        import os

        self._grid_combo = combo
        self._path = path
        self._metrics = metrics
        self._scorer = None
        self.model_id = os.path.basename(path)[: -len(".h2o3")]
        self._parms = dict(combo)

    def predict(self, frame):
        if self._scorer is None:
            from ..mojo import load_model

            self._scorer = load_model(self._path)
        return self._scorer.predict(frame)

    def __getattr__(self, name):
        metrics = object.__getattribute__(self, "_metrics")
        if name in ("auc", "rmse", "mse", "logloss", "mae", "r2",
                    "mean_per_class_error", "pr_auc", "accuracy"):
            val = metrics.get(name, float("nan"))
            return lambda *a, **kw: val
        raise AttributeError(name)


def _jnorm(v):
    """Normalize a hyper-param structure into JSON space (tuples → lists,
    np scalars → str). Checkpoint state is round-tripped through json.dump,
    so every comparison between live and restored params must normalize
    both sides or an identical sweep fails to match its own records."""
    import json

    return json.loads(json.dumps(v, default=str))


class H2OGridSearch:
    def __init__(
        self,
        model,
        hyper_params: Dict[str, Sequence[Any]],
        grid_id: Optional[str] = None,
        search_criteria: Optional[Dict[str, Any]] = None,
        recovery_dir: Optional[str] = None,
        parallelism: int = 1,
    ):
        # `model` may be an estimator class or a template instance (h2o-py
        # accepts both)
        if isinstance(model, type):
            self.model_class = model
            self.base_parms: Dict[str, Any] = {}
        else:
            self.model_class = type(model)
            self.base_parms = {
                k: v for k, v in model._parms.items() if not k.startswith("_")
            }
        # plain-python values throughout: numpy scalars (np.arange hyper
        # ranges) would crash every JSON dump of grid state downstream
        self.hyper_params = {
            k: [x.item() if isinstance(x, np.generic) else x for x in v]
            for k, v in hyper_params.items()}
        self.grid_id = grid_id or f"grid_{int(time.time())}"
        self.search_criteria = dict(search_criteria or {"strategy": "Cartesian"})
        self.recovery_dir = recovery_dir
        # upstream H2OGridSearch `parallelism`: how many candidate builds
        # may be in flight at once (runtime/trainpool.py — results and the
        # resulting leaderboard stay in submission order, so any value
        # produces the same grid as parallelism=1)
        self.parallelism = max(int(parallelism or 1), 1)
        self.models: List = []
        self.failed: List[Dict] = []
        self._done_combos: List[Dict] = []  # restored on recovery

    # -- grid auto-recovery (hex/grid/GridSearch recovery + RecoveryHandler) -
    def _state_path(self):
        import os

        return os.path.join(self.recovery_dir, f"{self.grid_id}.grid.json")

    def _save_state(self):
        import json
        import os

        os.makedirs(self.recovery_dir, exist_ok=True)
        state = dict(
            grid_id=self.grid_id,
            model_module=self.model_class.__module__,
            model_class=self.model_class.__name__,
            base_parms={k: v for k, v in self.base_parms.items()
                        if isinstance(v, (int, float, str, bool, list, type(None)))},
            hyper_params=self.hyper_params,
            search_criteria=self.search_criteria,
            done_combos=self._done_combos,
            data_fp=getattr(self, "_data_fp", None),
        )
        with open(self._state_path(), "w") as f:
            json.dump(state, f)

    @staticmethod
    def load(recovery_dir: str, grid_id: str) -> "H2OGridSearch":
        """Re-import a checkpointed grid; already-built models are restored
        from their artifacts (so the leaderboard stays complete) and
        train() resumes only the remaining combos (h2o.load_grid / grid
        recovery_dir semantics)."""
        import importlib
        import json
        import os

        with open(os.path.join(recovery_dir, f"{grid_id}.grid.json")) as f:
            state = json.load(f)
        cls = getattr(importlib.import_module(state["model_module"]),
                      state["model_class"])
        g = H2OGridSearch(cls, state["hyper_params"], grid_id=state["grid_id"],
                          search_criteria=state["search_criteria"],
                          recovery_dir=recovery_dir)
        g.base_parms = state["base_parms"]
        # a record whose artifact is gone is dropped, not kept: keeping it
        # would exclude the combo from retraining while restoring nothing —
        # the model silently vanishes from the grid
        g._done_combos = []
        for d in state["done_combos"]:
            path = os.path.join(recovery_dir, d["file"])
            if os.path.exists(path):
                g._done_combos.append(d)
                g.models.append(_RecoveredModel(d["params"], path,
                                                d.get("metrics", {})))
        return g

    def _combos(self) -> List[Dict[str, Any]]:
        keys = list(self.hyper_params)
        combos = [
            dict(zip(keys, vals))
            for vals in itertools.product(*(self.hyper_params[k] for k in keys))
        ]
        strat = self.search_criteria.get("strategy", "Cartesian")
        if strat == "RandomDiscrete":
            seed = int(self.search_criteria.get("seed", 1234) or 1234)
            rng = np.random.default_rng(seed)
            rng.shuffle(combos)
            mm = self.search_criteria.get("max_models")
            if mm:
                combos = combos[: int(mm)]
        return combos

    def _auto_resume(self) -> None:
        """Sweep checkpoint/resume (hex.grid recovery): a killed sweep
        re-submitted with the same grid_id + recovery_dir + hyper space
        skips its already-trained combos — done-combo records and their
        model artifacts are restored from the state file WITHOUT requiring
        an explicit `H2OGridSearch.load` call. A state file whose hyper
        space or model class differs is someone else's sweep: it is left
        untouched and the grid trains from scratch (the done-combo filter
        would drop nothing anyway)."""
        import json as _json
        import os

        if (not self.recovery_dir or self._done_combos
                or not os.path.exists(self._state_path())):
            return
        try:
            with open(self._state_path()) as f:
                state = _json.load(f)
        except (ValueError, OSError):
            return
        from ..runtime.log import Log

        if (_jnorm(state.get("hyper_params")) != _jnorm(self.hyper_params)
                or state.get("model_class") != self.model_class.__name__
                or _jnorm(state.get("search_criteria"))
                != _jnorm(self.search_criteria)
                # data fingerprint: same sweep spec on DIFFERENT training
                # data must not restore the old data's models
                or state.get("data_fp") != getattr(self, "_data_fp", None)):
            Log.warn(f"grid {self.grid_id}: recovery state in "
                     f"{self.recovery_dir} does not match this sweep's "
                     "hyper space/model/data; ignoring it")
            return
        self._done_combos = []
        for d in state.get("done_combos") or []:
            path = os.path.join(self.recovery_dir, d["file"])
            if os.path.exists(path):
                self._done_combos.append(d)
                self.models.append(_RecoveredModel(d["params"], path,
                                                   d.get("metrics", {})))
            else:
                # dropped, not kept: a record without its artifact must
                # retrain, or the combo silently vanishes from the grid
                Log.warn(f"grid {self.grid_id}: artifact {d['file']} "
                         "missing from recovery_dir; combo will retrain")
        restored = len(self._done_combos)
        if restored:
            from ..runtime import trainpool as _tp

            _tp.record_resumed(restored)

    def train(self, x=None, y=None, training_frame: Optional[Frame] = None,
              parallelism: Optional[int] = None, **kw):
        if getattr(training_frame, "_is_remote", False):
            if kw:
                raise TypeError(
                    "remote grid search forwards x/y/training_frame only; "
                    f"unsupported kwargs for the wire path: {sorted(kw)}")
            return self._remote_train(x, y, training_frame)
        t0 = time.time()
        budget = float(self.search_criteria.get("max_runtime_secs", 0) or 0)
        if training_frame is not None:
            # shape + column names stand in for frame identity across
            # process restarts (auto-generated frame keys don't survive one)
            self._data_fp = dict(
                y=str(y),
                x=sorted(str(c) for c in x) if x is not None else None,
                nrow=int(training_frame.nrow),
                ncol=int(training_frame.ncol),
                columns=[str(c) for c in training_frame.names])
        self._auto_resume()
        # compare in JSON space: restored done-combos carry lists where the
        # live sweep may carry tuples — raw == would retrain every combo
        done = [_jnorm(d["params"]) for d in self._done_combos]
        combos = [c for c in self._combos() if _jnorm(c) not in done]
        par = max(int(parallelism if parallelism is not None
                      else self.parallelism), 1)

        from ..runtime import trainpool as _tp

        if _tp.legacy():
            # H2O3_TRAIN_LEGACY=1: the seed sequential walk, verbatim — the
            # bench.py vs_seed comparator (no pool, no artifact cache)
            return self._train_sequential(combos, x, y, training_frame,
                                          t0, budget, **kw)

        import threading

        ckpt_lock = threading.Lock()

        def _candidate(combo):
            def fn(job):
                parms = dict(self.base_parms)
                parms.update(combo)
                parms.pop("model_id", None)
                est = self.model_class(**parms)
                # the pool's child job rides into the estimator so /3/Jobs
                # cancellation of the grid reaches scoring-boundary safe
                # points inside the candidate's training loop
                est._external_job = job
                est.train(x=x, y=y, training_frame=training_frame, **kw)
                est._grid_combo = combo
                if self.recovery_dir:
                    # checkpoint failures must not mark the built model
                    # failed; a combo only counts done once its artifact
                    # exists on disk (seed semantics, now under a lock)
                    with ckpt_lock:
                        try:
                            self._record_done(est, combo)
                            self._save_state()
                        except (TypeError, OSError):
                            pass
                return est
            return fn

        pool = _tp.TrainPool(par, label=self.grid_id,
                             parent_job=getattr(self, "_external_job", None))
        recs = pool.run(
            [(f"combo{i}", _candidate(c)) for i, c in enumerate(combos)],
            stop_when=(lambda: bool(budget) and time.time() - t0 > budget))
        for combo, rec in zip(combos, recs):
            if rec.ok:
                self.models.append(rec.result)
            elif rec.status == "failed":
                # failed combos are recorded, the walk continues
                self.failed.append({"params": combo, "error": rec.error})
        return self

    def _train_sequential(self, combos, x, y, training_frame, t0, budget,
                          **kw):
        """The seed-era sequential walk (H2O3_TRAIN_LEGACY comparator)."""
        for combo in combos:
            if budget and time.time() - t0 > budget:
                break
            parms = dict(self.base_parms)
            parms.update(combo)
            parms.pop("model_id", None)
            try:
                est = self.model_class(**parms)
                est.train(x=x, y=y, training_frame=training_frame, **kw)
                est._grid_combo = combo
                self.models.append(est)
            except Exception as e:
                self.failed.append({"params": combo, "error": str(e)})
                continue
            if self.recovery_dir:
                try:
                    self._record_done(est, combo)
                    self._save_state()
                except (TypeError, OSError):
                    pass
        return self

    def _record_done(self, est, combo) -> None:
        """Export one built model's artifact into recovery_dir and append
        its done-combo record. Filenames are combo-indexed (NOT model_id,
        which restarts per process and would clobber earlier runs')."""
        from ..mojo import save_model

        fname = f"{self.grid_id}_combo{len(self._done_combos)}.h2o3"
        save_model(est, self.recovery_dir, filename=fname, force=True)
        m = est.model
        metrics = dict(m.training_metrics._ser()
                       if m.training_metrics else {})
        if m.cross_validation_metrics is not None:
            metrics.update(m.cross_validation_metrics._ser())
        metrics = {k: v for k, v in metrics.items()
                   if isinstance(v, (int, float, str))}
        self._done_combos.append(
            dict(params=combo, file=fname, metrics=metrics))

    def save(self, grid_directory: str) -> str:
        """Export the trained grid — state file + one artifact per built
        model — so `h2o.load_grid(grid_directory)` restores it in another
        process (`h2o.save_grid`; upstream Grid.exportBinary +
        RecoveryHandler state). Grids trained WITHOUT a recovery_dir are
        supported: their done-combo records are built here from the live
        estimators."""
        import json as _json
        import os

        prev = self.recovery_dir
        # artifacts referenced by _done_combos live wherever they were last
        # exported (recovery_dir during train, or a prior save() target) —
        # they must travel with the state file that references them
        src_dir = prev or getattr(self, "_artifact_dir", None)
        self.recovery_dir = grid_directory
        try:
            if src_dir and os.path.abspath(src_dir) != os.path.abspath(
                    grid_directory):
                import shutil

                os.makedirs(grid_directory, exist_ok=True)
                for d in self._done_combos:
                    src = os.path.join(src_dir, d["file"])
                    if os.path.exists(src):
                        shutil.copy2(src, grid_directory)
            seen = {_json.dumps(d["params"], sort_keys=True, default=str)
                    for d in self._done_combos}
            for est in self.models:
                if isinstance(est, _RecoveredModel):
                    continue            # already in _done_combos
                combo = getattr(est, "_grid_combo", None)
                if combo is None:
                    raise TypeError(
                        "save_grid: grid model carries no combo record — "
                        "remotely-trained grids keep their artifacts on the "
                        "SERVER (download models individually)")
                if _json.dumps(combo, sort_keys=True, default=str) in seen:
                    continue
                self._record_done(est, combo)
            self._save_state()
            self._artifact_dir = grid_directory
        finally:
            self.recovery_dir = prev
        return grid_directory

    def _remote_train(self, x, y, training_frame):
        """Grid search against an attached server — POST `/99/Grid/{algo}`
        with the hyper space + base params, poll the job, hydrate
        REST-backed models from `/99/Grids/{id}` (h2o-py's H2OGridSearch
        REST choreography)."""
        import json as _json
        import urllib.parse as _up

        from ..client import RemoteModel

        from ..client import encode_nondefault_params

        conn = training_frame.conn
        cls = self.model_class
        params = encode_nondefault_params(self.base_parms, cls)
        params.update(training_frame=training_frame.key, response_column=y,
                      grid_id=self.grid_id,
                      hyper_parameters=_json.dumps(self.hyper_params),
                      search_criteria=_json.dumps(self.search_criteria))
        if self.parallelism != 1:
            params["parallelism"] = self.parallelism
        if x is not None:
            params["x"] = _json.dumps(list(x))
        out = conn.post(f"/99/Grid/{cls.algo}", **params)
        budget = float(self.search_criteria.get("max_runtime_secs", 0) or 0)
        conn.wait_for_job(out["job"]["key"]["name"],
                          timeout=budget + 600.0 if budget else 86_400.0)
        got = conn.get(f"/99/Grids/{_up.quote(self.grid_id, safe='')}")
        self.models = [RemoteModel(conn, d["name"])
                       for d in got["model_ids"]]
        # combo params are not recoverable over the wire: keep the local
        # dict shape with an explicit None
        self.failed = [{"params": None, "error": e}
                       for e in got.get("failure_details", []) if e]
        return self

    # -- h2o-py surface ------------------------------------------------------
    def get_grid(self, sort_by: Optional[str] = None, decreasing: Optional[bool] = None):
        if sort_by:
            if decreasing is None:
                decreasing = sort_by.lower() in ("auc", "pr_auc", "accuracy", "r2")
            def _nfolds(m):
                if getattr(m, "_parms", None) is not None:
                    return m._parms.get("nfolds", 0)
                ps = getattr(m, "params", None)   # REST-backed models
                return (ps or {}).get("nfolds", 0) if isinstance(ps, dict) \
                    else 0

            xval = any(_nfolds(m) for m in self.models)

            def metric(m):
                try:
                    fn = getattr(m, sort_by, None)
                    if callable(fn):
                        v = fn(xval=xval)
                        return float("nan") if v is None else float(v)
                    if hasattr(m, "_m"):       # REST-backed: metrics dict
                        v = getattr(m._m(xval=xval), sort_by, None)
                        v = v() if callable(v) else v
                        if v is None:
                            v = m._m(xval=xval).get(sort_by)
                        return float("nan") if v is None else float(v)
                    return getattr(m.model._m(xval=xval), sort_by)
                except Exception:
                    return float("nan")

            self.models.sort(key=lambda m: (np.isnan(metric(m)), -metric(m) if decreasing else metric(m)))
        return self

    @property
    def model_ids(self) -> List[str]:
        return [m.model_id for m in self.models]

    def __iter__(self):
        return iter(self.models)

    def __len__(self):
        return len(self.models)

    def __getitem__(self, i):
        return self.models[i]

    def summary(self):
        return [
            {**getattr(m, "_grid_combo", {}), "model_id": m.model_id}
            for m in self.models
        ]
