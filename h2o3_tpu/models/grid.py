"""H2OGridSearch — hyperparameter search.

Reference parity: `h2o-algos/src/main/java/hex/grid/GridSearch.java`,
`hex/grid/HyperSpaceWalker.java` (Cartesian + RandomDiscrete strategies,
`search_criteria`: max_models / max_runtime_secs / seed / stopping_*),
`hex/grid/Grid.java` (keyed store of built models) and the client surface
`h2o-py/h2o/grid/grid_search.py` (`H2OGridSearch(model, hyper_params,
search_criteria)`, `get_grid(sort_by, decreasing)`).

Models in a grid are independent → on a pod this is embarrassingly parallel
across hosts; round 1 builds sequentially (each build already uses the full
mesh), which matches the reference's default parallelism=1 sequential walk.
"""

from __future__ import annotations

import itertools
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..frame.frame import Frame


class H2OGridSearch:
    def __init__(
        self,
        model,
        hyper_params: Dict[str, Sequence[Any]],
        grid_id: Optional[str] = None,
        search_criteria: Optional[Dict[str, Any]] = None,
    ):
        # `model` may be an estimator class or a template instance (h2o-py
        # accepts both)
        if isinstance(model, type):
            self.model_class = model
            self.base_parms: Dict[str, Any] = {}
        else:
            self.model_class = type(model)
            self.base_parms = {
                k: v for k, v in model._parms.items() if not k.startswith("_")
            }
        self.hyper_params = {k: list(v) for k, v in hyper_params.items()}
        self.grid_id = grid_id or f"grid_{int(time.time())}"
        self.search_criteria = dict(search_criteria or {"strategy": "Cartesian"})
        self.models: List = []
        self.failed: List[Dict] = []

    def _combos(self) -> List[Dict[str, Any]]:
        keys = list(self.hyper_params)
        combos = [
            dict(zip(keys, vals))
            for vals in itertools.product(*(self.hyper_params[k] for k in keys))
        ]
        strat = self.search_criteria.get("strategy", "Cartesian")
        if strat == "RandomDiscrete":
            seed = int(self.search_criteria.get("seed", 1234) or 1234)
            rng = np.random.default_rng(seed)
            rng.shuffle(combos)
            mm = self.search_criteria.get("max_models")
            if mm:
                combos = combos[: int(mm)]
        return combos

    def train(self, x=None, y=None, training_frame: Optional[Frame] = None, **kw):
        t0 = time.time()
        budget = float(self.search_criteria.get("max_runtime_secs", 0) or 0)
        for combo in self._combos():
            if budget and time.time() - t0 > budget:
                break
            parms = dict(self.base_parms)
            parms.update(combo)
            parms.pop("model_id", None)
            try:
                est = self.model_class(**parms)
                est.train(x=x, y=y, training_frame=training_frame, **kw)
                est._grid_combo = combo
                self.models.append(est)
            except Exception as e:  # failed combos are recorded, walk continues
                self.failed.append({"params": combo, "error": str(e)})
        return self

    # -- h2o-py surface ------------------------------------------------------
    def get_grid(self, sort_by: Optional[str] = None, decreasing: Optional[bool] = None):
        if sort_by:
            if decreasing is None:
                decreasing = sort_by.lower() in ("auc", "pr_auc", "accuracy", "r2")
            xval = any(m._parms.get("nfolds", 0) for m in self.models)

            def metric(m):
                try:
                    return getattr(m, sort_by)(xval=xval) if callable(getattr(m, sort_by, None)) \
                        else getattr(m.model._m(xval=xval), sort_by)
                except Exception:
                    return float("nan")

            self.models.sort(key=lambda m: (np.isnan(metric(m)), -metric(m) if decreasing else metric(m)))
        return self

    @property
    def model_ids(self) -> List[str]:
        return [m.model_id for m in self.models]

    def __iter__(self):
        return iter(self.models)

    def __len__(self):
        return len(self.models)

    def __getitem__(self, i):
        return self.models[i]

    def summary(self):
        return [
            {**getattr(m, "_grid_combo", {}), "model_id": m.model_id}
            for m in self.models
        ]
