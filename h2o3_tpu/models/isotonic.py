"""H2OIsotonicRegressionEstimator — weighted isotonic (monotone) regression.

Reference parity: `h2o-algos/src/main/java/hex/isotonic/IsotonicRegression.java`
+ `hex/isotonic/PoolAdjacentViolatorsDriver.java`: sort by the single feature,
run weighted pool-adjacent-violators, keep the (x, y) knots; scoring clips or
NAs out-of-bounds inputs per `out_of_bounds`. Estimator surface
`h2o-py/h2o/estimators/isotonic_regression.py`.

TPU note: PAV is an inherently sequential merge of adjacent pools, done once
on host over the (small) sorted aggregate; scoring is a vectorized
`jnp.interp`-style lookup, trivially row-sharded.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..frame.frame import Frame
from .metrics import ModelMetricsRegression
from .model_base import H2OEstimator, H2OModel


def pav(x: np.ndarray, y: np.ndarray, w: np.ndarray):
    """Weighted pool-adjacent-violators on (x, y, w) sorted by x.

    Returns the isotonic knot arrays (thresholds_x, thresholds_y) — one knot
    per final pool, as the reference's PoolAdjacentViolatorsDriver produces.
    """
    order = np.argsort(x, kind="mergesort")
    x, y, w = x[order], y[order], w[order]
    # collapse duplicate x to weighted means first (reference aggregates ties)
    ux, start = np.unique(x, return_index=True)
    end = np.append(start[1:], len(x))
    wy = np.array([np.sum(y[s:e] * w[s:e]) for s, e in zip(start, end)])
    ws = np.array([np.sum(w[s:e]) for s, e in zip(start, end)])
    my = wy / np.maximum(ws, 1e-300)

    # stack-based PAV: each pool = (sum_wy, sum_w, first_idx)
    vals = np.empty(len(ux))
    wts = np.empty(len(ux))
    first = np.empty(len(ux), np.int64)
    top = 0
    for i in range(len(ux)):
        vals[top], wts[top], first[top] = my[i] * ws[i], ws[i], i
        top += 1
        while top > 1 and vals[top - 2] / wts[top - 2] >= vals[top - 1] / wts[top - 1]:
            vals[top - 2] += vals[top - 1]
            wts[top - 2] += wts[top - 1]
            top -= 1
    means = vals[:top] / wts[:top]
    # knots at the first x of each pool plus the trailing x, so interpolation
    # reproduces the step/linear fit on pool boundaries
    tx, ty = [], []
    for k in range(top):
        lo = first[k]
        hi = (first[k + 1] - 1) if k + 1 < top else len(ux) - 1
        tx.append(ux[lo])
        ty.append(means[k])
        if hi > lo:
            tx.append(ux[hi])
            ty.append(means[k])
    return np.asarray(tx, np.float64), np.asarray(ty, np.float64)


class IsotonicRegressionModel(H2OModel):
    algo = "isotonicregression"

    def __init__(self, params, x, y, tx, ty, out_of_bounds):
        super().__init__(params)
        self.x = x
        self.y = y
        self.thresholds_x = tx
        self.thresholds_y = ty
        self.out_of_bounds = out_of_bounds

    def _score(self, col: np.ndarray) -> np.ndarray:
        tx, ty = self.thresholds_x, self.thresholds_y
        p = np.interp(col, tx, ty)
        if self.out_of_bounds.lower() == "na":
            p = np.where((col < tx[0]) | (col > tx[-1]), np.nan, p)
        p = np.where(np.isnan(col), np.nan, p)
        return p

    def predict(self, test_data: Frame) -> Frame:
        p = self._score(test_data.vec(self.x).numeric_np())
        return Frame.from_dict({"predict": p})

    def _make_metrics(self, frame: Frame):
        p = self._score(frame.vec(self.x).numeric_np())
        yv = frame.vec(self.y).numeric_np()
        ok = ~np.isnan(p) & ~np.isnan(yv)
        return ModelMetricsRegression.make(yv[ok], p[ok])


class H2OIsotonicRegressionEstimator(H2OEstimator):
    algo = "isotonicregression"
    _param_defaults = dict(out_of_bounds="NA", custom_metric_func=None)

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]):
        from .model_base import warn_host_solver

        warn_host_solver('isotonicregression', train.nrow, 2000000)
        if len(x) != 1:
            raise ValueError("isotonicregression expects exactly one feature column")
        xn = x[0]
        col = train.vec(xn).numeric_np()
        yv = train.vec(y).numeric_np()
        wcol = self._parms.get("weights_column")
        w = train.vec(wcol).numeric_np() if wcol else np.ones_like(yv)
        ok = ~np.isnan(col) & ~np.isnan(yv)
        tx, ty = pav(col[ok], yv[ok], w[ok])
        model = IsotonicRegressionModel(
            self, xn, y, tx, ty, str(self._parms.get("out_of_bounds", "NA"))
        )
        model.training_metrics = model._make_metrics(train)
        if valid is not None:
            model.validation_metrics = model._make_metrics(valid)
        return model

    def _cv_predict(self, model: IsotonicRegressionModel, frame: Frame) -> np.ndarray:
        return model._score(frame.vec(model.x).numeric_np())
