"""H2OWord2vecEstimator — word embeddings.

Reference parity: `h2o-algos/src/main/java/hex/word2vec/Word2Vec.java`
(skip-gram with hierarchical softmax / negative sampling, HogWild updates,
`WordVectorTrainer` MRTask) and the client surface
`h2o-py/h2o/estimators/word2vec.py` (`find_synonyms`, `transform` with
aggregate_method="AVERAGE", pre-trained import).

TPU rebuild: HogWild per-word races → synchronous minibatch skip-gram with
negative sampling (SGNS): each step gathers (center, context, negatives)
batches built host-side from the unigram table, and the device does two
embedding matmuls + a sigmoid loss under jit — the dense MXU formulation of
what the reference scatters one word at a time.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from .metrics import ModelMetricsBase
from .model_base import H2OEstimator, H2OModel


class Word2VecModel(H2OModel):
    algo = "word2vec"

    def __init__(self, params, vocab: List[str], vectors: np.ndarray):
        super().__init__(params)
        self.vocab = vocab
        self.index: Dict[str, int] = {w: i for i, w in enumerate(vocab)}
        self.vectors = vectors  # (V, dim)
        self.x = []
        self.y = None

    def find_synonyms(self, word: str, count: int = 20) -> Dict[str, float]:
        if word not in self.index:
            return {}
        v = self.vectors[self.index[word]]
        norms = np.linalg.norm(self.vectors, axis=1) * max(np.linalg.norm(v), 1e-12)
        sims = self.vectors @ v / np.maximum(norms, 1e-12)
        order = np.argsort(-sims)
        out = {}
        for i in order:
            if self.vocab[i] == word:
                continue
            out[self.vocab[i]] = float(sims[i])
            if len(out) >= count:
                break
        return out

    def transform(self, words_frame: Frame, aggregate_method: str = "NONE") -> Frame:
        """words → vectors; AVERAGE aggregates consecutive non-NA runs
        (h2o's sentence embedding convention: NA rows delimit sentences)."""
        col = words_frame.vecs()[0]
        words = col.to_numpy() if col.type == "string" else np.asarray(
            [col.domain[c] if c >= 0 else None for c in np.asarray(col.data)],
            dtype=object,
        )
        dim = self.vectors.shape[1]
        if aggregate_method.upper() == "NONE":
            out = np.full((len(words), dim), np.nan)
            for i, w in enumerate(words):
                if w is not None and w in self.index:
                    out[i] = self.vectors[self.index[w]]
            return Frame.from_dict({f"C{j+1}": out[:, j] for j in range(dim)})
        # AVERAGE
        sents, cur = [], []
        for w in words:
            if w is None:
                sents.append(cur)
                cur = []
            else:
                cur.append(w)
        sents.append(cur)
        out = np.full((len(sents), dim), np.nan)
        for i, sent in enumerate(sents):
            vecs = [self.vectors[self.index[w]] for w in sent if w in self.index]
            if vecs:
                out[i] = np.mean(vecs, axis=0)
        return Frame.from_dict({f"C{j+1}": out[:, j] for j in range(dim)})

    def predict(self, test_data: Frame) -> Frame:
        return self.transform(test_data)

    def _make_metrics(self, frame):
        return ModelMetricsBase()


class H2OWord2vecEstimator(H2OEstimator):
    algo = "word2vec"
    supervised = False
    _param_defaults = dict(
        vec_size=100,
        min_word_freq=5,
        window_size=5,
        sent_sample_rate=0.001,
        init_learning_rate=0.025,
        epochs=5,
        negative_samples=5,
        norm_model="HSM",
        word_model="SkipGram",
        pre_trained=None,
    )

    @staticmethod
    def from_external(frame: Frame) -> Word2VecModel:
        """Import pre-trained embeddings (h2o.word2vec pre_trained path):
        first column words, rest the vector. Word labels are decoded PER ROW
        (an enum column's domain is sorted, not row-ordered — rows must pair
        with their own matrix row)."""
        words = frame.vecs()[0]
        if words.type == "string":
            labels = [str(w) for w in words.to_numpy()]
        elif words.type == "enum":
            dom = np.asarray(words.domain + [None], dtype=object)
            labels = [str(w) for w in dom[np.asarray(words.data)]]
        else:
            labels = [str(w) for w in words.numeric_np()]
        mat = np.column_stack([v.numeric_np() for v in frame.vecs()[1:]])
        est = H2OWord2vecEstimator()
        return Word2VecModel(est, labels, mat)

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]) -> Word2VecModel:
        p = self._parms
        seed = p["_actual_seed"]
        col = train.vecs()[0]
        if col.type == "string":
            words = col.to_numpy()
        elif col.type == "enum":
            dom = np.asarray(col.domain + [None], dtype=object)
            words = dom[np.asarray(col.data)]
        else:
            raise ValueError("word2vec needs a string/enum column of words")

        min_freq = int(p.get("min_word_freq", 5))
        toks = [w for w in words if w is not None]
        uniq, counts = np.unique(np.asarray(toks, dtype=object), return_counts=True)
        keep = counts >= min_freq
        vocab = [str(w) for w in uniq[keep]]
        freq = counts[keep].astype(np.float64)
        V = len(vocab)
        if V == 0:
            raise ValueError(f"no words with frequency >= {min_freq}")
        index = {w: i for i, w in enumerate(vocab)}
        seq = np.asarray([index.get(w, -1) if w is not None else -1 for w in words],
                         np.int64)

        dim = int(p.get("vec_size", 100))
        window = int(p.get("window_size", 5))
        neg = int(p.get("negative_samples", 5))
        lr = float(p.get("init_learning_rate", 0.025))
        epochs = int(p.get("epochs", 5))

        # skip-gram pairs within sentences (NA-delimited)
        centers, contexts = [], []
        nvalid = len(seq)
        for i in range(nvalid):
            if seq[i] < 0:
                continue
            for d in range(1, window + 1):
                j = i + d
                if j >= nvalid or seq[j] < 0:
                    break
                centers.append(seq[i]); contexts.append(seq[j])
                centers.append(seq[j]); contexts.append(seq[i])
        if not centers:
            raise ValueError("no skip-gram pairs (input too short)")
        centers = np.asarray(centers, np.int32)
        contexts = np.asarray(contexts, np.int32)

        # unigram^0.75 negative-sampling table
        probs = freq ** 0.75
        probs = probs / probs.sum()

        rng = np.random.default_rng(seed)
        key = jax.random.PRNGKey(seed & 0x7FFFFFFF)
        Wc = (rng.random((V, dim)).astype(np.float32) - 0.5) / dim
        Wo = np.zeros((V, dim), np.float32)
        Wc, Wo = jnp.asarray(Wc), jnp.asarray(Wo)

        @jax.jit
        def step(Wc, Wo, c_idx, o_idx, n_idx, lr_t):
            def loss_fn(params):
                Wc_, Wo_ = params
                vc = Wc_[c_idx]                     # (B, d)
                vo = Wo_[o_idx]                     # (B, d)
                vn = Wo_[n_idx]                     # (B, neg, d)
                pos = jax.nn.log_sigmoid(jnp.sum(vc * vo, axis=1))
                negs = jax.nn.log_sigmoid(-jnp.einsum("bd,bnd->bn", vc, vn)).sum(axis=1)
                return -jnp.mean(pos + negs)

            g = jax.grad(loss_fn)((Wc, Wo))
            return Wc - lr_t * g[0], Wo - lr_t * g[1]

        B = min(8192, len(centers))
        steps_per_epoch = max(len(centers) // B, 1)
        total = epochs * steps_per_epoch
        t = 0
        for ep in range(epochs):
            perm = rng.permutation(len(centers))
            for s in range(steps_per_epoch):
                sel = perm[s * B : (s + 1) * B]
                n_idx = rng.choice(V, size=(len(sel), neg), p=probs).astype(np.int32)
                lr_t = np.float32(lr * max(1 - t / total, 1e-4))
                Wc, Wo = step(Wc, Wo, jnp.asarray(centers[sel]),
                              jnp.asarray(contexts[sel]), jnp.asarray(n_idx), lr_t)
                t += 1

        model = Word2VecModel(self, vocab, np.asarray(Wc))
        model.training_metrics = ModelMetricsBase(nobs=len(centers))
        return model


Word2Vec = H2OWord2vecEstimator
