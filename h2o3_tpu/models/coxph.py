"""H2OCoxProportionalHazardsEstimator — Cox PH survival regression.

Reference parity: `h2o-algos/src/main/java/hex/coxph/CoxPH.java`
(`CoxPHTask` accumulates risk-set sums per event time; Newton-Raphson on the
partial log-likelihood; `ties` ∈ {efron, breslow}), `hex/coxph/CoxPHModel.java`
(coef/exp(coef)/se(coef), loglik, concordance). Estimator surface
`h2o-py/h2o/estimators/coxph.py` (`stop_column`, `ties`, `stratify_by`).

TPU shape: sort rows by stop time (descending), then every risk-set sum
Σ_{t_j ≥ t_i} exp(η_j)·{1, x_j, x_j x_j'} is a cumulative sum — the
reference's CoxPHTask map/reduce becomes three `jnp.cumsum`s per Newton
step; the p×p Newton solve is a tiny host Cholesky.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from .metrics import ModelMetricsBase
from .model_base import DataInfo, H2OEstimator, H2OModel


@functools.partial(jax.jit, static_argnames=())
def _cox_sums(X, eta, w):
    """Cumulative risk-set sums over rows sorted by descending stop time:
    rs0[i] = Σ_{j≤i} w e^η, rs1[i] = Σ w e^η x, rs2[i] = Σ w e^η x x'."""
    r = w * jnp.exp(eta)
    rs0 = jnp.cumsum(r)
    rs1 = jnp.cumsum(r[:, None] * X, axis=0)
    rs2 = jnp.cumsum(r[:, None, None] * (X[:, :, None] * X[:, None, :]), axis=0)
    return rs0, rs1, rs2


def _partial_ll(X, eta, w, event, last_in_tie, tie_first, tie_size, ties,
                start_sorted=None, start_perm=None, times=None):
    """Partial log-likelihood + gradient + (negative) Hessian.

    Rows are pre-sorted by descending stop time; `last_in_tie[i]` is the last
    row index (inclusive) sharing row i's stop time, so risk-set sums are the
    cumulative sums evaluated there. With a start column (counting-process
    data), rows whose (start, stop] interval does not cover the event time are
    removed by subtracting start-sorted cumulative sums:
    Σ_{start_j ≥ t} (entered strictly before t ⇒ at risk)."""
    rs0, rs1, rs2 = _cox_sums(X, eta, w)
    rs0 = np.asarray(rs0, np.float64)
    rs1 = np.asarray(rs1, np.float64)
    rs2 = np.asarray(rs2, np.float64)
    Xn = np.asarray(X, np.float64)
    etan = np.asarray(eta, np.float64)
    wn = np.asarray(w, np.float64)
    r = wn * np.exp(etan)

    if start_perm is not None:
        # cumulative sums in descending-start order (device cumsum again)
        cs0, cs1, cs2 = _cox_sums(X[start_perm], eta[start_perm], w[start_perm])
        cs0 = np.asarray(cs0, np.float64)
        cs1 = np.asarray(cs1, np.float64)
        cs2 = np.asarray(cs2, np.float64)

    ev = event.astype(bool)
    p = Xn.shape[1]
    ll, grad, hess = 0.0, np.zeros(p), np.zeros((p, p))
    # group events by tie group (same stop time)
    for g0 in np.unique(tie_first[ev]):
        gsize = tie_size[g0]
        rows = np.arange(g0, g0 + gsize)
        erows = rows[ev[rows]]
        d = len(erows)
        if d == 0:
            continue
        li = last_in_tie[g0]
        s0, s1, s2 = rs0[li], rs1[li], rs2[li]
        if start_perm is not None:
            # remove subjects not yet entered at this event time t:
            # start_sorted is descending; k = #{j : start_j >= t}
            t = times[g0]
            k = int(np.searchsorted(-start_sorted, -t, side="right"))
            if k > 0:
                s0 = s0 - cs0[k - 1]
                s1 = s1 - cs1[k - 1]
                s2 = s2 - cs2[k - 1]
        sw = wn[erows].sum()
        ll += (wn[erows] * etan[erows]).sum()
        grad += (wn[erows, None] * Xn[erows]).sum(axis=0)
        if ties == "efron" and d > 1:
            e0 = r[erows].sum()
            e1 = (r[erows, None] * Xn[erows]).sum(axis=0)
            e2 = (r[erows, None, None] * (Xn[erows][:, :, None] * Xn[erows][:, None, :])).sum(axis=0)
            for k in range(d):
                f = k / d
                d0 = s0 - f * e0
                d1 = s1 - f * e1
                d2 = s2 - f * e2
                ll -= (sw / d) * np.log(max(d0, 1e-300))
                grad -= (sw / d) * d1 / d0
                hess += (sw / d) * (d2 / d0 - np.outer(d1, d1) / d0**2)
        else:  # breslow
            ll -= sw * np.log(max(s0, 1e-300))
            grad -= sw * s1 / s0
            hess += sw * (s2 / s0 - np.outer(s1, s1) / s0**2)
    return ll, grad, hess


class CoxPHModel(H2OModel):
    algo = "coxph"

    def __init__(self, params, x, y, dinfo, beta, se, loglik, loglik_null,
                 concordance, n_event, stop_col):
        super().__init__(params)
        self.x = list(x)
        self.y = y
        self.dinfo = dinfo
        self.beta = beta
        self.se_coef = se
        self.loglik = loglik
        self.loglik_null = loglik_null
        self.concordance = concordance
        self.n_event = n_event
        self.stop_col = stop_col

    def coef(self):
        return dict(zip(self.dinfo.coef_names, self.beta))

    def coefficients_table(self):
        z = self.beta / np.maximum(self.se_coef, 1e-300)
        return [
            dict(name=n, coef=float(b), exp_coef=float(np.exp(b)),
                 se_coef=float(s), z_coef=float(zz))
            for n, b, s, zz in zip(self.dinfo.coef_names, self.beta, self.se_coef, z)
        ]

    def predict(self, test_data: Frame) -> Frame:
        """Linear predictor (log relative hazard), centered like the reference."""
        X = self.dinfo.transform(test_data)
        return Frame.from_dict({"lp": X @ self.beta})

    def _make_metrics(self, frame: Frame):
        return self.training_metrics


def _concordance(time, event, lp):
    """Harrell's C: concordant / comparable pairs (CoxPHModel concordance)."""
    order = np.argsort(time, kind="mergesort")
    time, event, lp = time[order], event[order], lp[order]
    conc = ties = comp = 0.0
    ev_idx = np.nonzero(event)[0]
    for i in ev_idx:
        later = time > time[i]
        if not later.any():
            continue
        comp += later.sum()
        conc += (lp[later] < lp[i]).sum()
        ties += (lp[later] == lp[i]).sum()
    if comp == 0:
        return float("nan")
    return float((conc + 0.5 * ties) / comp)


class H2OCoxProportionalHazardsEstimator(H2OEstimator):
    algo = "coxph"
    _param_defaults = dict(
        ties="efron",
        stop_column=None,
        start_column=None,
        stratify_by=None,
        use_all_factor_levels=False,
        init=0.0,
        lre_min=9.0,
        max_iterations=20,
        interactions=None,
    )

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]) -> CoxPHModel:
        from .model_base import warn_host_solver

        warn_host_solver('coxph', train.nrow, 500000)
        p = self._parms
        stop_col = p.get("stop_column")
        if stop_col is None:
            raise ValueError("coxph requires stop_column")
        ties = str(p.get("ties", "efron")).lower()
        start_col = p.get("start_column")
        strat_cols = p.get("stratify_by") or []
        if isinstance(strat_cols, str):
            strat_cols = [strat_cols]
        x = [c for c in x if c not in (stop_col, start_col) and c not in strat_cols]
        dinfo = DataInfo(train, x, standardize=False,
                         use_all_factor_levels=bool(p.get("use_all_factor_levels", False)))
        X = dinfo.fit_transform(train).astype(np.float64)
        # center columns — the reference solves on centered covariates
        xbar = X.mean(axis=0)
        Xc = X - xbar
        t = train.vec(stop_col).numeric_np()
        t0 = train.vec(start_col).numeric_np() if start_col else None
        yv = train.vec(y)
        event = (np.asarray(yv.data, np.float64) if yv.type == "enum"
                 else yv.numeric_np()).astype(np.float64)
        wcol = p.get("weights_column")
        w = train.vec(wcol).numeric_np() if wcol else np.ones(len(t))
        n = len(t)

        # strata = distinct combinations of the stratify_by columns; the
        # partial likelihood is computed per-stratum and summed (CoxPH strata)
        if strat_cols:
            keys = np.zeros(n, np.int64)
            for c in strat_cols:
                v = train.vec(c)
                codes = (np.asarray(v.data, np.int64) if v.type == "enum"
                         else v.numeric_np().astype(np.int64))
                keys = keys * (codes.max() + 2) + codes
            _, strata = np.unique(keys, return_inverse=True)
        else:
            strata = np.zeros(n, np.int64)

        # per-stratum sorted structures (built once)
        groups = []
        for s in np.unique(strata):
            rows = np.nonzero(strata == s)[0]
            ts_raw = t[rows]
            order = np.argsort(-ts_raw, kind="mergesort")
            rows = rows[order]
            ts = t[rows]
            m = len(rows)
            tie_first = np.zeros(m, np.int64)
            tie_size = np.zeros(m, np.int64)
            last_in_tie = np.zeros(m, np.int64)
            i = 0
            while i < m:
                j = i
                while j + 1 < m and ts[j + 1] == ts[i]:
                    j += 1
                tie_first[i : j + 1] = i
                tie_size[i] = j - i + 1
                last_in_tie[i : j + 1] = j
                i = j + 1
            g = dict(
                rows=rows,
                Xj=jnp.asarray(Xc[rows], jnp.float32),
                Xs=Xc[rows],
                wj=jnp.asarray(w[rows], jnp.float32),
                es=event[rows],
                tie_first=tie_first, tie_size=tie_size, last_in_tie=last_in_tie,
                start_sorted=None, start_perm=None, times=None,
            )
            if t0 is not None:
                sp = np.argsort(-t0[rows], kind="mergesort")
                g["start_perm"] = jnp.asarray(sp, jnp.int32)
                g["start_sorted"] = t0[rows][sp]
                g["times"] = ts
            groups.append(g)

        pdim = Xc.shape[1]

        def accumulate(beta):
            ll, grad, hess = 0.0, np.zeros(pdim), np.zeros((pdim, pdim))
            for g in groups:
                eta = jnp.asarray(g["Xs"] @ beta, jnp.float32)
                l, gr, he = _partial_ll(
                    g["Xj"], eta, g["wj"], g["es"], g["last_in_tie"],
                    g["tie_first"], g["tie_size"], ties,
                    start_sorted=g["start_sorted"], start_perm=g["start_perm"],
                    times=g["times"],
                )
                ll += l
                grad += gr
                hess += he
            return ll, grad, hess

        beta = np.full(pdim, float(p.get("init", 0.0)))
        ll = ll_null = None
        if not beta.any():
            ll_null = accumulate(beta)[0]
        for it in range(int(p.get("max_iterations", 20))):
            ll, grad, hess = accumulate(beta)
            try:
                step = np.linalg.solve(hess + 1e-9 * np.eye(pdim), grad)
            except np.linalg.LinAlgError:
                step = np.linalg.lstsq(hess, grad, rcond=None)[0]
            beta = beta + step
            if np.max(np.abs(step)) < 1e-8:
                break
        if ll_null is None:
            ll_null = accumulate(np.zeros(pdim))[0]
        ll, grad, hess = accumulate(beta)
        try:
            se = np.sqrt(np.maximum(np.diag(np.linalg.inv(hess + 1e-9 * np.eye(pdim))), 0))
        except np.linalg.LinAlgError:
            se = np.full(pdim, np.nan)
        conc = _concordance(t, event, X @ beta)
        model = CoxPHModel(self, x, y, dinfo, beta, se, float(ll), float(ll_null),
                           conc, int(event.sum()), stop_col)
        model.training_metrics = ModelMetricsBase(nobs=n, description=f"concordance={conc:.4f}")
        return model

    def _cv_predict(self, model: CoxPHModel, frame: Frame) -> np.ndarray:
        return model.predict(frame).vec("lp").numeric_np()


CoxPH = H2OCoxProportionalHazardsEstimator
