"""H2OAggregatorEstimator — exemplar-based dataset aggregation.

Reference parity: `h2o-algos/src/main/java/hex/aggregator/Aggregator.java`:
single-pass radius-based exemplar selection (a row joins the nearest exemplar
within `radius`, else becomes a new exemplar with count 1), with the radius
rescaled between passes until the exemplar count lands within
`rel_tol_num_exemplars` of `target_num_exemplars`. Output is the aggregated
frame: one row per exemplar plus a `counts` column. Estimator surface
`h2o-py/h2o/estimators/aggregator.py`.

TPU note: the distance of a block of rows against the current exemplar set is
one (block × p) @ (p × E) matmul on the MXU; only rows that fail the radius
test fall back to the (rare) sequential exemplar-append path on host.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from .metrics import ModelMetricsBase
from .model_base import DataInfo, H2OEstimator, H2OModel

_BLOCK = 4096


@jax.jit
def _assign_block(block: jnp.ndarray, ex: jnp.ndarray, n_ex: jnp.ndarray):
    """Nearest (live) exemplar id + squared distance for a block of rows.
    `ex` is a fixed-capacity buffer; rows ≥ n_ex are masked out, so the
    compiled shape only changes when capacity doubles."""
    d2 = (
        jnp.sum(block * block, axis=1, keepdims=True)
        - 2.0 * block @ ex.T
        + jnp.sum(ex * ex, axis=1)[None, :]
    )
    d2 = jnp.where(jnp.arange(ex.shape[0])[None, :] < n_ex, d2, jnp.inf)
    j = jnp.argmin(d2, axis=1)
    return j, jnp.take_along_axis(d2, j[:, None], axis=1)[:, 0]


def _aggregate(X: np.ndarray, radius2: float):
    """One pass: returns (exemplar_row_indices, member_counts)."""
    n, pdim = X.shape
    cap = 256
    ex_buf = np.zeros((cap, pdim), np.float32)
    ex_buf[0] = X[0]
    n_ex = 1
    ex_idx = [0]
    counts = [1]
    i = 1
    while i < n:
        block = X[i : i + _BLOCK]
        j, d2 = _assign_block(jnp.asarray(block), jnp.asarray(ex_buf),
                              jnp.int32(n_ex))
        j = np.asarray(j)
        d2 = np.asarray(d2)
        ok = d2 <= radius2
        # rows within radius of an existing exemplar: bulk-assign
        for jj in j[ok]:
            counts[jj] += 1
        # the rest are processed in order — each may become a new exemplar
        # that absorbs later rows of the same block, so recompute locally
        rest_rows = np.nonzero(~ok)[0]
        for ridx in rest_rows:
            row = block[ridx]
            d2r = np.sum((ex_buf[:n_ex] - row) ** 2, axis=1)
            jj = int(np.argmin(d2r))
            if d2r[jj] <= radius2:
                counts[jj] += 1
            else:
                if n_ex == cap:  # grow capacity (power-of-two → few recompiles)
                    cap *= 2
                    nb = np.zeros((cap, pdim), np.float32)
                    nb[:n_ex] = ex_buf
                    ex_buf = nb
                ex_buf[n_ex] = row
                n_ex += 1
                ex_idx.append(i + int(ridx))
                counts.append(1)
        i += _BLOCK
    return np.asarray(ex_idx), np.asarray(counts, np.float64)


class AggregatorModel(H2OModel):
    algo = "aggregator"

    def __init__(self, params, x, dinfo, aggregated, exemplar_idx, counts):
        super().__init__(params)
        self.x = list(x)
        self.y = None
        self.dinfo = dinfo
        self._aggregated = aggregated
        self.exemplar_idx = exemplar_idx
        self.counts = counts

    @property
    def aggregated_frame(self) -> Frame:
        return self._aggregated

    def predict(self, test_data: Frame) -> Frame:
        raise ValueError("aggregator does not support predict(); use aggregated_frame")

    def _make_metrics(self, frame: Frame):
        return self.training_metrics


class H2OAggregatorEstimator(H2OEstimator):
    algo = "aggregator"
    supervised = False
    _param_defaults = dict(
        target_num_exemplars=5000,
        rel_tol_num_exemplars=0.5,
        transform="NORMALIZE",
        num_iteration_without_new_exemplar=500,
        save_mapping_frame=False,
    )

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]) -> AggregatorModel:
        from .model_base import warn_host_solver

        warn_host_solver('aggregator', train.nrow, 200000)
        p = self._parms
        transform = p.get("transform", "NORMALIZE")
        dinfo = DataInfo(train, x, standardize=transform != "NONE",
                         use_all_factor_levels=True)
        X = dinfo.fit_transform(train).astype(np.float32)
        n, pdim = X.shape
        target = int(p.get("target_num_exemplars", 5000))
        tol = float(p.get("rel_tol_num_exemplars", 0.5))

        if target >= n:
            # fewer rows than requested exemplars: every row is an exemplar
            # (radius 0 — the reference's degenerate small-data case)
            idx, counts = np.arange(n), np.ones(n, np.float64)
        else:
            # radius search: bisection on log-radius until exemplar count is
            # within rel tolerance of target (Aggregator's radius rescale loop)
            r2 = float(pdim) * 0.1
            lo, hi = None, None
            best = None
            for _ in range(20):
                idx, counts = _aggregate(X, r2)
                e = len(idx)
                best = (idx, counts)
                if e > target * (1 + tol):   # too many exemplars → grow radius
                    lo = r2
                    r2 = r2 * 4 if hi is None else (r2 + hi) / 2
                elif e >= target * (1 - tol):
                    break
                else:                        # too few → shrink radius
                    hi = r2
                    r2 = r2 / 4 if lo is None else (r2 + lo) / 2
            idx, counts = best

        cols = {}
        for name in train.names:
            v = train.vec(name)
            taken = v.take(np.asarray(idx))
            cols[name] = taken
        agg = Frame(cols)
        agg["counts"] = counts
        model = AggregatorModel(self, x, dinfo, agg, idx, counts)
        model.training_metrics = ModelMetricsBase(nobs=n)
        return model


Aggregator = H2OAggregatorEstimator
