"""H2OExtendedIsolationForestEstimator — EIF anomaly detection.

Reference parity: `h2o-algos/src/main/java/hex/tree/isoforextended/
ExtendedIsolationForest.java` (+ `isolationtree/CompressedExtendedIsolationTree`):
each node splits on a random oblique hyperplane — direction n with
`extension_level`+1 non-zero components, intercept p drawn uniformly inside
the node's projected range; anomaly score 2^(−E[pathlen]/c(sample_size))
exactly as (axis-parallel) IsolationForest. Estimator surface
`h2o-py/h2o/estimators/extended_isolation_forest.py`.

TPU shape: a tree is a static heap of depth ceil(log2(sample_size)); one
level = a (rows × p)·(p) projection per node (gathered per-row direction),
`segment_min/max` for the per-node projected range, and an elementwise
route — the whole forest builds as one vmapped jitted program, no dynamic
node objects.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.frame import Frame
from .metrics import ModelMetricsBase
from .model_base import DataInfo, H2OEstimator, H2OModel


def _avg_path(n):
    """c(n): average unsuccessful-search path length in a BST (IF paper)."""
    n = np.maximum(n, 2.0)
    return 2.0 * (np.log(n - 1.0) + 0.5772156649) - 2.0 * (n - 1.0) / n


@functools.partial(jax.jit, static_argnames=("depth",))
def _build_eif_tree(X, dirs, us, depth: int):
    """Build one EIF tree over the (S, p) subsample.

    dirs: (T, p) random directions (already masked to extension level),
    us: (T,) U(0,1) draws for the intercepts. Returns (thr (T,), dirs,
    is_split (T,), path_len (S,)) where path_len includes the c(size)
    correction at the stopping node.
    """
    S = X.shape[0]
    T = dirs.shape[0]               # internal heap: 2^depth - 1
    Tfull = 2 ** (depth + 1) - 1    # + terminal level
    idx = jnp.zeros(S, jnp.int32)
    alive = jnp.ones(S, bool)
    thr_a = jnp.zeros(T, jnp.float32)
    split_a = jnp.zeros(T, bool)
    count_a = jnp.zeros(Tfull, jnp.float32)  # training rows per node at stop

    for d in range(depth):
        L = 2 ** d
        base = L - 1
        node = base + idx  # heap id per row
        nd = dirs[node]                       # (S, p)
        proj = jnp.sum(X * nd, axis=1)        # (S,)
        big = jnp.float32(3.4e38)
        pmin = jax.ops.segment_min(jnp.where(alive, proj, big),
                                   idx, num_segments=L)
        pmax = jax.ops.segment_max(jnp.where(alive, proj, -big),
                                   idx, num_segments=L)
        cnt = jax.ops.segment_sum(alive.astype(jnp.float32),
                                  idx, num_segments=L)
        can_split = (cnt > 1.0) & (pmax > pmin)
        thr = pmin + us[base : base + L] * (pmax - pmin)
        thr_a = thr_a.at[base : base + L].set(jnp.where(can_split, thr, 0.0))
        split_a = split_a.at[base : base + L].set(can_split)
        # leaf nodes at this level keep their row count (for the c(n) credit)
        count_a = count_a.at[base : base + L].set(jnp.where(can_split, 0.0, cnt))

        node_splits = can_split[idx]
        go_right = alive & node_splits & (proj > thr[idx])
        idx = jnp.where(alive & node_splits,
                        2 * idx + go_right.astype(jnp.int32), idx)
        alive = alive & node_splits

    # terminal level: count rows per cell
    Lf = 2 ** depth
    cnt_f = jax.ops.segment_sum(alive.astype(jnp.float32), idx, num_segments=Lf)
    count_a = count_a.at[Lf - 1 :].set(cnt_f)
    return thr_a, split_a, count_a


@functools.partial(jax.jit, static_argnames=("depth",))
def _score_eif_forest(X, dirs, thrs, splits, counts, depth: int):
    """Path length (depth + c(leaf_size) credit) of each row through every
    tree — (ntrees, N)."""

    def one_tree(dirs_t, thr_t, split_t, count_t):
        N = X.shape[0]
        idx = jnp.zeros(N, jnp.int32)
        depth_stop = jnp.full(N, float(depth), jnp.float32)
        stop_node = jnp.zeros(N, jnp.int32)
        live = jnp.ones(N, bool)
        for d in range(depth):
            L = 2 ** d
            base = L - 1
            node = base + idx
            s = split_t[node]
            proj = jnp.sum(X * dirs_t[node], axis=1)
            stopping = live & ~s
            depth_stop = jnp.where(stopping, jnp.float32(d), depth_stop)
            stop_node = jnp.where(stopping, node, stop_node)
            live = live & s
            go_right = live & (proj > thr_t[node])
            idx = jnp.where(live, 2 * idx + go_right.astype(jnp.int32), idx)
        stop_node = jnp.where(live, 2 ** depth - 1 + idx, stop_node)
        # unresolved-subtree credit: c(n) for leaves holding n>1 training rows
        nleaf = count_t[stop_node]
        credit = jnp.where(
            nleaf > 1.5,
            2.0 * (jnp.log(jnp.maximum(nleaf - 1.0, 1.0)) + 0.5772156649)
            - 2.0 * (nleaf - 1.0) / jnp.maximum(nleaf, 1.0),
            0.0,
        )
        return depth_stop + credit

    return jax.vmap(one_tree)(dirs, thrs, splits, counts)


class ExtendedIsolationForestModel(H2OModel):
    algo = "extendedisolationforest"

    def __init__(self, params, x, dinfo, dirs, thrs, splits, counts, depth, sample_size):
        super().__init__(params)
        self.x = list(x)
        self.y = None
        self.dinfo = dinfo
        self.dirs = dirs          # (ntrees, T, p)
        self.thrs = thrs          # (ntrees, T)
        self.splits = splits      # (ntrees, T)
        self.counts = counts      # (ntrees, 2T+1) training rows per node
        self.depth = depth
        self.sample_size = sample_size

    def predict(self, test_data: Frame) -> Frame:
        X = jnp.asarray(self.dinfo.transform(test_data))
        pl = np.asarray(_score_eif_forest(X, self.dirs, self.thrs, self.splits,
                                          self.counts, self.depth), np.float64)
        mean_length = pl.mean(axis=0)
        score = 2.0 ** (-mean_length / _avg_path(self.sample_size))
        return Frame.from_dict({"anomaly_score": score, "mean_length": mean_length})

    def _make_metrics(self, frame: Frame):
        return self.training_metrics


class H2OExtendedIsolationForestEstimator(H2OEstimator):
    algo = "extendedisolationforest"
    supervised = False
    _param_defaults = dict(
        ntrees=100,
        sample_size=256,
        extension_level=0,
        disable_training_metrics=True,
    )

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]):
        p = self._parms
        dinfo = DataInfo(train, x, standardize=False, use_all_factor_levels=True)
        X = dinfo.fit_transform(train)
        n, pdim = X.shape
        ntrees = int(p.get("ntrees", 100))
        S = min(int(p.get("sample_size", 256)), n)
        depth = max(int(np.ceil(np.log2(max(S, 2)))), 1)
        T = 2 ** depth - 1  # internal heap levels 0..depth-1
        ext = int(p.get("extension_level", 0))
        if not 0 <= ext <= pdim - 1:
            raise ValueError(f"extension_level must be in [0, {pdim-1}]")
        seed = int(self._parms.get("_actual_seed", 1234))
        rng = np.random.default_rng(seed)

        # dispatch all tree builds async; ONE stacked D2H at the end (per-tree
        # np.asarray syncs pay the remote-TPU tunnel RTT ntrees times)
        dirs_all, thr_dev, split_dev, count_dev = [], [], [], []
        for t in range(ntrees):
            rows = rng.choice(n, size=S, replace=False)
            Xs = jnp.asarray(X[rows])
            d = rng.normal(size=(T, pdim)).astype(np.float32)
            # extension_level e ⇒ e+1 non-zero components per direction
            if ext < pdim - 1:
                mask = np.zeros((T, pdim), np.float32)
                for i in range(T):
                    keep = rng.choice(pdim, size=ext + 1, replace=False)
                    mask[i, keep] = 1.0
                d = d * mask
            d /= np.maximum(np.linalg.norm(d, axis=1, keepdims=True), 1e-12)
            us = rng.uniform(size=T).astype(np.float32)
            thr, split, counts = _build_eif_tree(Xs, jnp.asarray(d),
                                                 jnp.asarray(us), depth)
            dirs_all.append(d)
            thr_dev.append(thr)
            split_dev.append(split)
            count_dev.append(counts)

        model = ExtendedIsolationForestModel(
            self, x, dinfo,
            jnp.asarray(np.stack(dirs_all)),
            jnp.stack(thr_dev), jnp.stack(split_dev), jnp.stack(count_dev),
            depth, S,
        )
        model.training_metrics = ModelMetricsBase(nobs=n)
        return model


ExtendedIsolationForest = H2OExtendedIsolationForestEstimator
