"""Streamed tree step — the out-of-core GBM/DRF driver (ISSUE 14).

The in-core fused path builds each tree as ONE jitted program over a
device-resident code matrix. When the packed matrix exceeds the device
budget, this module builds the SAME tree from per-block jitted pieces: the
level loop walks the `BlockStore`'s row-blocks in canonical order — while
the histogram kernel consumes block *b*, the H2D upload of block *b+1* is
already dispatched (`prefetch`, the `_score_event_async`
dispatch-before-block pattern) — and accumulates per-block histogram
partials with the same deterministic left-to-right f32 fold
(`ordered_axis_fold`) the in-core ``shard_mode="blocks"`` reduction uses.

Bit-exactness contract: every computation here reuses the in-core path's
own building blocks — `ops.histogram.run_block_kernel` (each partial is
exactly one block of the blocked in-core reduction), `_fused_level_best`
(the single-pass split search), `_lookup_int`/`packed_row_values` (the
partition gathers), `value_at` (the margin update) and the `_one_tree`
RNG-key derivation chain — so a streamed fit with sampling OFF is
BIT-IDENTICAL to the in-core fit sharing its block count S (pinned in
tests/test_tree_stream.py: forest, varimp, scoring history, early-stop
tree count, predictions). Per-level passes are FUSED per block visit:
entering level d, one block visit applies level d-1's partition and
accumulates level d's sibling-left histogram partial, so a tree streams
(depth+1)·S block reads, not 2·depth·S.

Host-histogram blocks never touch `pure_callback`: the per-block
accumulate runs `_host_hist_cb` directly on the ONE dedicated worker
thread (`ops.histogram.host_hist_direct`) — same math, bit-exact, and
immune to the warm-thread callback hang documented in docs/perf.md.

Gradient-based sampling (the paper's GOSS-shaped §sampling): past the
warm-up trees, keep the top-|g| rows plus an amplified random rest, gather
them into a compact packed sample, and build the tree on THAT — the
per-level histogram passes stream a fraction of the bytes; only the final
margin update walks every block once. Opt-in (``goss=True``), GBM
single-margin fits only, and by construction not bit-comparable to the
unsampled path.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import packing
from ..runtime import qos as _qos
from ..runtime import supervisor as _supervisor
from ..ops.histogram import (host_hist_direct, ordered_axis_fold,
                             resolve_method, run_block_kernel)
from . import distributions as dist_mod
from . import tree as treelib
from .tree import (_ONEHOT_LOOKUP_MAX, _fused_level_best, _lookup_bool,
                   _lookup_int, _row_feature_value, heap_size)

# -- jitted pieces ----------------------------------------------------------
#
# Each is a small program traced once per shape and dispatched per block /
# per level. The math inside mirrors `tree.build_tree` line for line (the
# comments there hold); only the orchestration differs.


@functools.partial(jax.jit, static_argnames=(
    "mode", "problem", "dist", "tw", "qa", "k"))
def _grads_jit(margins, y_d, mode: str, problem: str, dist: str,
               tw: float, qa: float, k: int):
    if mode == "drf":
        return -y_d[:, k], jnp.ones_like(y_d[:, k])
    if problem == "multinomial":
        p = jax.nn.softmax(margins, axis=1)
        return p[:, k] - y_d[:, k], p[:, k] * (1 - p[:, k])
    return dist_mod.grad_hess(dist, margins[:, 0], y_d[:, 0],
                              tweedie_power=tw, alpha=qa)


@functools.partial(jax.jit, static_argnames=(
    "npad", "F", "row_sampling", "col_sampling"))
def _sample_jit(key, rate_a, w_a, hp, npad: int, F: int,
                row_sampling: bool, col_sampling: bool):
    """The `_one_tree` sampling prologue, key chain included."""
    krow, kcol, ktree = jax.random.split(jax.random.fold_in(key, 0), 3)
    if row_sampling:
        row_mask = (jax.random.uniform(krow, (npad,)) < rate_a
                    ).astype(jnp.float32)
        wt = w_a * row_mask
    else:
        row_mask = jnp.ones(npad, jnp.float32)
        wt = w_a
    if col_sampling:
        fm = (jax.random.uniform(kcol, (F,)) < hp[6]).astype(jnp.float32)
        fm = fm.at[0].set(jnp.maximum(fm[0], 1 - fm.sum().clip(0, 1)))
    else:
        fm = jnp.ones(F, jnp.float32)
    return row_mask, wt, fm, ktree


@jax.jit
def _scale_jit(hp, m):
    return (hp[4] * jnp.power(hp[5], jnp.asarray(m, jnp.float32))
            ).astype(jnp.float32)


def _partition(codes_b, idx_b, bf, bb, do_split, L: int, pack_bits: int):
    """One block's row partition under a level decision — the build_tree
    partition gathers, verbatim (block-local packed reads are exact:
    block boundaries sit on pack-group boundaries)."""
    rf = _lookup_int(bf, idx_b, L)
    rb = _lookup_int(bb, idx_b, L)
    rs = _lookup_bool(do_split, idx_b, L)
    if pack_bits:
        rcode = packing.packed_row_values(codes_b, rf, pack_bits)
    else:
        rcode = _row_feature_value(codes_b, rf)
    go_right = (rcode > rb) & rs
    return 2 * idx_b + go_right.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("L", "pack_bits"))
def _partition_jit(codes_b, idx_b, bf, bb, do_split, L: int, pack_bits: int):
    return _partition(codes_b, idx_b, bf, bb, do_split, L, pack_bits)


@functools.partial(jax.jit, static_argnames=(
    "nbins", "method", "pack_bits", "row_chunk"))
def _first_pass_jit(codes_b, g_b, h_b, wt_b, nbins: int, method: str,
                    pack_bits: int, row_chunk: Optional[int]):
    """Level-0 block partial: root histogram over one block."""
    node = jnp.zeros(g_b.shape[0], jnp.int32)
    vals = jnp.stack([wt_b, g_b * wt_b, h_b * wt_b]).astype(jnp.float32)
    return run_block_kernel(method, codes_b, node, vals, 1, nbins,
                            pack_bits, row_chunk)


@functools.partial(jax.jit, static_argnames=(
    "L_prev", "nbins", "method", "pack_bits", "row_chunk"))
def _level_pass_jit(codes_b, idx_b, g_b, h_b, wt_b, bf, bb, do_split,
                    L_prev: int, nbins: int, method: str, pack_bits: int,
                    row_chunk: Optional[int]):
    """The fused per-block visit of level d: apply level d-1's partition,
    then accumulate level d's sibling-LEFT histogram partial (right =
    parent − left happens on the merged histograms)."""
    idx_b = _partition(codes_b, idx_b, bf, bb, do_split, L_prev, pack_bits)
    is_left = (idx_b % 2 == 0)
    w_eff = wt_b * is_left.astype(wt_b.dtype)
    vals = jnp.stack([w_eff, g_b * w_eff, h_b * w_eff]).astype(jnp.float32)
    part = run_block_kernel(method, codes_b, idx_b // 2, vals, L_prev,
                            nbins, pack_bits, row_chunk)
    return idx_b, part


def _leaf_block_tot(ids_b, vals_b, nseg: int, use_oh: bool):
    """One block's exact {Σw, Σg·w, Σh·w} leaf totals — `_leaf_totals.one`."""
    if use_oh:
        oh = (ids_b[:, None] == jnp.arange(nseg, dtype=jnp.int32)[None, :]
              ).astype(jnp.float32)
        return jnp.dot(vals_b, oh, preferred_element_type=jnp.float32,
                       precision=jax.lax.Precision.HIGHEST).T
    return jax.ops.segment_sum(vals_b.T, ids_b, num_segments=nseg)


@functools.partial(jax.jit, static_argnames=(
    "L_prev", "nseg", "use_oh", "pack_bits"))
def _leaf_pass_jit(codes_b, idx_b, g_b, h_b, wt_b, bf, bb, do_split,
                   L_prev: int, nseg: int, use_oh: bool, pack_bits: int):
    """Final block visit: last level's partition + exact leaf totals."""
    idx_b = _partition(codes_b, idx_b, bf, bb, do_split, L_prev, pack_bits)
    vals = jnp.stack([wt_b, g_b * wt_b, h_b * wt_b])
    return idx_b, _leaf_block_tot(idx_b, vals, nseg, use_oh)


@jax.jit
def _fold_jit(parts):
    """Deterministic left-to-right merge of stacked block partials — the
    SAME `ordered_axis_fold` the in-core blocked reduction pins."""
    return ordered_axis_fold(parts, None)


@jax.jit
def _sibling_merge_jit(hist_prev, left):
    right = hist_prev - left
    L = 2 * left.shape[0]
    return jnp.stack([left, right], axis=1).reshape((L,) + left.shape[1:])


@functools.partial(jax.jit, static_argnames=("nbins", "has_keep"))
def _level_decide_jit(hist, active, feat_mask, keep, edges, hp, gain_pf,
                      nbins: int, has_keep: bool):
    """Merged-histogram level decision: node values, fused split search,
    varimp fold and raw thresholds — build_tree's dense-level body."""
    F = edges.shape[0]
    wsum = hist[..., 0].sum(axis=2)[:, 0]
    gsum = hist[..., 1].sum(axis=2)[:, 0]
    hsum = hist[..., 2].sum(axis=2)[:, 0]
    gthr = jnp.sign(gsum) * jnp.maximum(jnp.abs(gsum) - hp[3], 0.0)
    node_val = (-gthr / (hsum + hp[2] + 1e-12)).astype(jnp.float32)
    node_val = jnp.clip(node_val, -hp[7], hp[7])
    best_gain, bf, bb, _, _ = _fused_level_best(
        hist, active, feat_mask, keep if has_keep else None, nbins,
        hp[0], hp[2], hp[3], gsum, hsum, wsum)
    do_split = best_gain > jnp.maximum(hp[1], 1e-10)
    gain_pf = gain_pf + jax.ops.segment_sum(
        jnp.where(do_split, best_gain, 0.0).astype(jnp.float32), bf,
        num_segments=F)
    pad_edges = jnp.concatenate(
        [edges.astype(jnp.float32), jnp.full((F, 1), jnp.inf, jnp.float32)],
        axis=1)
    bthr = pad_edges[bf, jnp.minimum(bb, nbins - 2)]
    return node_val, wsum, do_split, bf, bb, bthr, gain_pf


@jax.jit
def _leaf_values_jit(tot, hp):
    gthr_f = jnp.sign(tot[:, 1]) * jnp.maximum(jnp.abs(tot[:, 1]) - hp[3],
                                               0.0)
    leaf_val = (-gthr_f / (tot[:, 2] + hp[2] + 1e-12)).astype(jnp.float32)
    leaf_val = jnp.clip(leaf_val, -hp[7], hp[7])
    return leaf_val, tot[:, 0].astype(jnp.float32)


@functools.partial(jax.jit, donate_argnums=(0,), static_argnames=("k",))
def _margin_add_jit(margins, leaf_vals, k: int):
    return margins.at[:, k].add(leaf_vals)


@jax.jit
def _pack_jit(feat, bin_, thr, is_split, value, covers):
    """Tree fields + covers → one (K, T, 6) f32 array (shared_tree._pack)."""
    return jnp.stack(
        [feat.astype(jnp.float32), bin_.astype(jnp.float32), thr,
         is_split.astype(jnp.float32), value, covers], axis=-1)


@functools.partial(jax.jit, static_argnames=("pack_bits", "max_depth"))
def _predict_block_jit(tree, codes_b, pack_bits: int, max_depth: int):
    return treelib.predict_codes_packed(tree, codes_b, pack_bits, max_depth)


class _ResidentBlocks:
    """Trivial provider over already-resident device blocks (the GOSS
    compact sample) — same surface as BlockStore where the level loop
    needs it."""

    def __init__(self, dev_blocks: List, host_blocks: List[np.ndarray]):
        self._dev = dev_blocks
        self.host_blocks = host_blocks

    def get(self, b: int):
        return self._dev[b]

    def fetch_host(self, b: int) -> np.ndarray:
        return self.host_blocks[b]

    def prefetch(self, b: int) -> None:
        pass


class StreamedTreeStep:
    """Drop-in replacement for the driver's jitted `tree_jit`: the same
    (margins, oob_sum, oob_cnt, codes, y, w, rate, edges, mono, hp, key,
    m) → (margins, oob_sum, oob_cnt, packed, gains, overflow) contract,
    built from per-block programs over a `BlockStore` instead of one
    monolithic program over a resident matrix. `codes` is ignored (the
    store holds the matrix); `mono` must be all-zero (monotone fits are
    gated in-core)."""

    def __init__(self, cfg, store, seed: int = 0,
                 goss: Optional[Dict] = None):
        if cfg.n_shards <= 0 or cfg.npad % cfg.n_shards:
            raise ValueError("streamed step needs an aligned block grid")
        self.cfg = cfg
        self.store = store
        self.S = int(cfg.n_shards)
        self.rows = cfg.npad // self.S
        self.seed = int(seed)
        self.goss = goss
        if goss:
            a, b = goss["top_rate"], goss["other_rate"]
            frac = min(a + b * 1.25 + 0.02, 1.0)
            cap = int(cfg.npad * frac) + 8
            self.goss_cap = min(cfg.npad, ((cap + 7) // 8) * 8)

    # -- helpers -----------------------------------------------------------

    def _method_for(self, n_nodes: int) -> dict:
        return resolve_method(n_nodes, self.cfg.nbins, self.cfg.hist_method,
                              axis_name=None)

    def _host_rows(self, g, h, wt):
        """Host copies of the per-row vectors for host-method kernels
        (free on CPU, where the host method is the only place this
        runs)."""
        return (np.asarray(g, np.float32), np.asarray(h, np.float32),
                np.asarray(wt, np.float32))

    # -- the streamed build_tree (dense depthwise, fused split) ------------

    def _build_streamed(self, provider, S: int, rows: int, g, h, wt, fm,
                        edges, hp, key):
        cfg = self.cfg
        D, nbins, F = cfg.max_depth, cfg.nbins, cfg.F
        pack_bits = cfg.pack_bits
        T = heap_size(D)
        feat_a = jnp.zeros(T, jnp.int32)
        bin_a = jnp.zeros(T, jnp.int32)
        thr_a = jnp.zeros(T, jnp.float32)
        split_a = jnp.zeros(T, bool)
        value_a = jnp.zeros(T, jnp.float32)
        cover_a = jnp.zeros(T, jnp.float32)
        gain_pf = jnp.zeros(F, jnp.float32)
        active = jnp.ones(1, bool)
        idx_blocks = [jnp.zeros(rows, jnp.int32) for _ in range(S)]
        host_rows = None
        dec = None
        hist_prev = None
        key_b = key
        for d in range(D):
            L = 2 ** d
            L_kernel = 1 if d == 0 else L // 2
            sel = self._method_for(L_kernel)
            method, row_chunk = sel["method"], sel["row_chunk"]
            if method == "host" and host_rows is None:
                host_rows = self._host_rows(g, h, wt)
            parts = []
            for b in range(S):
                # per-BLOCK QoS yield: the streamed grid is the natural
                # preemption point — serving dispatches slot in between
                # block visits instead of behind a whole level
                _qos.yield_point("tree_block")
                # supervisor heartbeat (ISSUE 20): a streamed fit's chunk
                # boundaries can be minutes apart — per-block pulses keep
                # its liveness signal fresh for the failure detector
                _supervisor.pulse("tree_stream", d * S + b)
                codes_b = provider.get(b)
                if d == 0:
                    if method == "host":
                        g_np, h_np, wt_np = (a[b * rows:(b + 1) * rows]
                                             for a in host_rows)
                        vals = np.stack([wt_np, g_np * wt_np,
                                         h_np * wt_np]).astype(np.float32)
                        part = jnp.asarray(host_hist_direct(
                            provider.fetch_host(b),
                            np.zeros(rows, np.int32), vals, 1, nbins,
                            pack_bits))
                    else:
                        part = _first_pass_jit(
                            codes_b, g[b * rows:(b + 1) * rows],
                            h[b * rows:(b + 1) * rows],
                            wt[b * rows:(b + 1) * rows],
                            nbins, method, pack_bits, row_chunk)
                else:
                    if method == "host":
                        idx_b = _partition_jit(
                            codes_b, idx_blocks[b], *dec, L // 2, pack_bits)
                        idx_blocks[b] = idx_b
                        idx_np = np.asarray(idx_b, np.int32)
                        g_np, h_np, wt_np = (a[b * rows:(b + 1) * rows]
                                             for a in host_rows)
                        w_eff = wt_np * (idx_np % 2 == 0)
                        vals = np.stack([w_eff, g_np * w_eff,
                                         h_np * w_eff]).astype(np.float32)
                        part = jnp.asarray(host_hist_direct(
                            provider.fetch_host(b), idx_np // 2, vals,
                            L // 2, nbins, pack_bits))
                    else:
                        idx_b, part = _level_pass_jit(
                            codes_b, idx_blocks[b],
                            g[b * rows:(b + 1) * rows],
                            h[b * rows:(b + 1) * rows],
                            wt[b * rows:(b + 1) * rows], *dec,
                            L // 2, nbins, method, pack_bits, row_chunk)
                        idx_blocks[b] = idx_b
                # double buffer: block b's kernel is dispatched (async);
                # start block b+1's H2D now so transfer and compute overlap
                provider.prefetch((b + 1) % S)
                parts.append(part)
            merged = _fold_jit(jnp.stack(parts))
            hist = merged if d == 0 else _sibling_merge_jit(hist_prev,
                                                            merged)
            hist_prev = hist
            keep = None
            if cfg.has_mtries:
                key_b, sub = jax.random.split(key_b)
                keep = jax.random.uniform(sub, (L, F)) < hp[8]
                keep = keep.at[:, 0].set(keep[:, 0] | ~keep.any(axis=1))
            node_val, wsum, do_split, bf, bb, bthr, gain_pf = \
                _level_decide_jit(hist, active, fm, keep, edges, hp,
                                  gain_pf, nbins, keep is not None)
            base = L - 1
            value_a = value_a.at[base:base + L].set(node_val)
            cover_a = cover_a.at[base:base + L].set(
                wsum.astype(jnp.float32))
            feat_a = feat_a.at[base:base + L].set(
                jnp.where(do_split, bf, 0))
            bin_a = bin_a.at[base:base + L].set(jnp.where(do_split, bb, 0))
            thr_a = thr_a.at[base:base + L].set(
                jnp.where(do_split, bthr, 0.0))
            split_a = split_a.at[base:base + L].set(do_split)
            active = jnp.repeat(do_split, 2)
            dec = (bf, bb, do_split)
        # final level: exact per-cell totals, blocked + ordered fold
        Lf = 2 ** D
        basef = Lf - 1
        use_oh = Lf <= 2 * _ONEHOT_LOOKUP_MAX
        parts = []
        for b in range(S):
            _qos.yield_point("tree_block")
            codes_b = provider.get(b)
            idx_b, tot_b = _leaf_pass_jit(
                codes_b, idx_blocks[b], g[b * rows:(b + 1) * rows],
                h[b * rows:(b + 1) * rows], wt[b * rows:(b + 1) * rows],
                *dec, Lf // 2, Lf, use_oh, pack_bits)
            idx_blocks[b] = idx_b
            provider.prefetch((b + 1) % S)
            parts.append(tot_b)
        tot = _fold_jit(jnp.stack(parts))
        leaf_val, leaf_cover = _leaf_values_jit(tot, hp)
        value_a = value_a.at[basef:].set(leaf_val)
        cover_a = cover_a.at[basef:].set(leaf_cover)
        leaf_idx = jnp.concatenate(idx_blocks) + basef
        return (treelib.Tree(feat_a, bin_a, thr_a, split_a, value_a),
                leaf_idx, gain_pf, cover_a)

    # -- GOSS: gradient-based sampling ------------------------------------

    def _goss_active(self, m: int) -> bool:
        return self.goss is not None and m >= self.goss["start_tree"]

    def _gather_codes(self, sel: np.ndarray) -> np.ndarray:
        """Selected rows gathered from the HOST blocks into a compact
        full-width matrix — per-block unpack transients only."""
        cfg = self.cfg
        out = np.zeros((self.goss_cap, cfg.F),
                       np.uint8 if cfg.nbins <= 256 else np.uint16)
        rows, bits = self.rows, cfg.pack_bits
        blk = sel // rows
        pos = 0
        for b in np.unique(blk):
            rb = sel[blk == b] - b * rows
            # a restoring fetch: GOSS-on-disk reads only the blocks the
            # sample touches (all of them once, here) and the per-level
            # passes then stream just the compact sample — the disk tier
            # is where sampling pays most (arXiv 1806.11248)
            hb = self.store.fetch_host(int(b))
            dense = packing.unpack_host(hb, bits) if bits else hb
            out[pos:pos + len(rb)] = dense[rb]
            pos += len(rb)
        return out

    def _goss_tree(self, g, h, w_a, fm, edges, hp, ktree, m: int, scale):
        """One GOSS tree: build on the compact top-|g| + amplified-rest
        sample, then stream every block ONCE for the full-row margin
        update. Returns (scaled tree, gains, cover, full-row leaf
        values)."""
        cfg = self.cfg
        a, brate = self.goss["top_rate"], self.goss["other_rate"]
        amp = np.float32((1.0 - a) / brate)
        w_np = np.asarray(w_a, np.float32) > 0
        absg = np.where(w_np, np.abs(np.asarray(g, np.float32)), -1.0)
        n_real = max(int(w_np.sum()), 1)
        n_top = max(int(a * n_real), 1)
        # EXACTLY n_top rows (argpartition, deterministic for a given
        # input) — a `>= threshold` mask over-selects on tied |g| (e.g.
        # laplace/quantile sign-shaped gradients, where every row ties)
        # and the cap trim would then keep an index-biased subset
        top = np.zeros(absg.shape[0], bool)
        top[np.argpartition(absg, -n_top)[-n_top:]] = True
        rng = np.random.default_rng((self.seed + 7919 * (m + 1))
                                    & 0x7FFFFFFF)
        rest = (~top) & w_np & (rng.random(absg.shape[0])
                                < brate / max(1.0 - a, 1e-9))
        weight = np.where(top, np.float32(1.0),
                          np.where(rest, amp, np.float32(0.0))
                          ).astype(np.float32)
        sel = np.nonzero(weight > 0)[0]
        if len(sel) > self.goss_cap:
            sel = sel[:self.goss_cap]    # deterministic slack overflow trim
        cap = self.goss_cap
        codes_sel = self._gather_codes(sel)
        packed_sel = (packing.pack_host(codes_sel, cfg.pack_bits)
                      if cfg.pack_bits else codes_sel)
        dev = jnp.asarray(packed_sel)
        self.store.account_external_bytes(int(packed_sel.nbytes))
        sel_pad = np.zeros(cap, np.int32)
        sel_pad[:len(sel)] = sel
        sel_d = jnp.asarray(sel_pad)
        w_sel_np = np.zeros(cap, np.float32)
        w_sel_np[:len(sel)] = np.asarray(w_a, np.float32)[sel] * weight[sel]
        g_sel = jnp.take(g, sel_d)
        h_sel = jnp.take(h, sel_d)
        w_sel = jnp.asarray(w_sel_np)
        provider = _ResidentBlocks([dev], [packed_sel])
        tr, _idx, gains, cover = self._build_streamed(
            provider, 1, cap, g_sel, h_sel, w_sel, fm, edges, hp, ktree)
        tr = tr._replace(value=tr.value * scale)
        vals = []
        for b in range(self.S):
            _qos.yield_point("tree_block")
            codes_b = self.store.get(b)
            vals.append(_predict_block_jit(tr, codes_b, cfg.pack_bits,
                                           cfg.max_depth))
            self.store.prefetch((b + 1) % self.S)
        return tr, gains, cover, jnp.concatenate(vals)

    # -- the step ----------------------------------------------------------

    def __call__(self, margins, oob_sum, oob_cnt, codes_d, y_a, w_a,
                 rate_a, edges_a, mono, hp, key, m):
        cfg = self.cfg
        m_int = int(m)
        key_t = jax.random.fold_in(key, m_int)
        row_mask, wt, fm, ktree = _sample_jit(
            key_t, rate_a, w_a, hp, cfg.npad, cfg.F,
            not cfg.no_row_sampling, cfg.has_col_sampling)
        scale = _scale_jit(hp, m_int)
        trs, covs = [], []
        gains_acc = jnp.zeros(cfg.F, jnp.float32)
        oob_inc = None
        for k in range(cfg.K):
            ktree = jax.random.fold_in(ktree, k)
            g, h = _grads_jit(margins, y_a, cfg.mode, cfg.problem, cfg.dist,
                              cfg.tweedie_power, cfg.quantile_alpha, k)
            if self._goss_active(m_int):
                tr, gains, cover, leaf_vals = self._goss_tree(
                    g, h, w_a, fm, edges_a, hp, ktree, m_int, scale)
            else:
                tr, leaf_idx, gains, cover = self._build_streamed(
                    self.store, self.S, self.rows, g, h, wt, fm, edges_a,
                    hp, ktree)
                tr = tr._replace(value=tr.value * scale)
                leaf_vals = treelib.value_at(tr.value, leaf_idx)
            margins = _margin_add_jit(margins, leaf_vals, k)
            if cfg.mode == "drf":
                col = leaf_vals * (1.0 - row_mask)
                oob_inc = (col[:, None] if oob_inc is None
                           else jnp.concatenate([oob_inc, col[:, None]],
                                                axis=1))
            trs.append(tr)
            covs.append(cover)
            gains_acc = gains_acc + gains
        stacked = treelib.Tree(
            *[jnp.stack([getattr(t, f) for t in trs])
              for f in treelib.Tree._fields])
        covers = jnp.stack(covs)
        packed = _pack_jit(stacked.feat, stacked.bin, stacked.thr,
                           stacked.is_split, stacked.value, covers)
        if oob_inc is not None:
            oob_sum = oob_sum + oob_inc
            oob_cnt = oob_cnt + (1.0 - row_mask)
        return margins, oob_sum, oob_cnt, packed, gains_acc, jnp.int32(0)
