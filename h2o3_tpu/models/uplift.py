"""H2OUpliftRandomForestEstimator — uplift random forest.

Reference parity: `h2o-algos/src/main/java/hex/tree/uplift/UpliftDRF.java` +
`hex/tree/uplift/Divergence.java` (`uplift_metric` ∈ {KL, Euclidean,
ChiSquared}: split gain is the weighted divergence between the treatment and
control response distributions after vs before the split), leaf prediction =
p(y|treated) − p(y|control), metrics `hex/ModelMetricsBinomialUplift.java`
(AUUC / Qini). Estimator surface `h2o-py/h2o/estimators/uplift_random_forest.py`.

TPU shape: same heap-tree / histogram design as `tree.py`, but each level
builds TWO histograms (treatment rows, control rows) via the same
`tpu_hist` op with masked weights; the divergence gain is elementwise math
over the two cumulative histograms. Cross-host merge stays `lax.psum`.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..frame.binning import build_bins
from ..frame.frame import Frame
from ..ops.histogram import build_histograms
from .metrics import ModelMetricsBase
from .model_base import H2OEstimator, H2OModel
from .shared_tree import frame_to_matrix
from . import tree as treelib

_EPS = 1e-6


def _divergence(pt, pc, metric: str):
    pt = jnp.clip(pt, _EPS, 1 - _EPS)
    pc = jnp.clip(pc, _EPS, 1 - _EPS)
    if metric == "KL":
        return pt * jnp.log(pt / pc) + (1 - pt) * jnp.log((1 - pt) / (1 - pc))
    if metric == "ChiSquared":
        return (pt - pc) ** 2 / pc + ((1 - pt) - (1 - pc)) ** 2 / (1 - pc)
    return (pt - pc) ** 2 + ((1 - pt) - (1 - pc)) ** 2  # Euclidean


@functools.partial(
    jax.jit,
    static_argnames=("max_depth", "nbins", "min_rows", "metric", "axis_name", "mtries"),
)
def build_uplift_tree(
    codes, y, w_t, w_c, edges,
    max_depth: int, nbins: int, min_rows: float = 10.0,
    metric: str = "KL", axis_name: Optional[str] = None,
    mtries: int = 0, key=None,
):
    """One uplift tree. w_t/w_c are row weights masked to treatment/control
    (0 elsewhere — also handles sampling/padding). Leaf value = p_t − p_c."""
    N, F = codes.shape
    T = treelib.heap_size(max_depth)
    feat_a = jnp.zeros(T, jnp.int32)
    bin_a = jnp.zeros(T, jnp.int32)
    thr_a = jnp.zeros(T, jnp.float32)
    split_a = jnp.zeros(T, bool)
    value_a = jnp.zeros(T, jnp.float32)
    idx = jnp.zeros(N, jnp.int32)
    active = jnp.ones(1, bool)
    if key is None:
        key = jax.random.PRNGKey(0)

    for d in range(max_depth + 1):
        L = 2 ** d
        base = L - 1
        ht = build_histograms(codes, idx, y, jnp.zeros_like(y), w_t,
                              L, nbins, axis_name=axis_name)  # {n_t, Σy_t, 0}
        hc = build_histograms(codes, idx, y, jnp.zeros_like(y), w_c,
                              L, nbins, axis_name=axis_name)
        nt = ht[..., 0].sum(axis=2)[:, 0]   # (L,)
        yt = ht[..., 1].sum(axis=2)[:, 0]
        nc = hc[..., 0].sum(axis=2)[:, 0]
        yc = hc[..., 1].sum(axis=2)[:, 0]
        pt_node = yt / jnp.maximum(nt, _EPS)
        pc_node = yc / jnp.maximum(nc, _EPS)
        value_a = value_a.at[base : base + L].set(
            (pt_node - pc_node).astype(jnp.float32)
        )
        if d == max_depth:
            break

        cnt_t, cy_t = jnp.cumsum(ht[..., 0], axis=2), jnp.cumsum(ht[..., 1], axis=2)
        cnt_c, cy_c = jnp.cumsum(hc[..., 0], axis=2), jnp.cumsum(hc[..., 1], axis=2)
        NT, YT = nt[:, None, None], yt[:, None, None]
        NC, YC = nc[:, None, None], yc[:, None, None]
        ptL = cy_t / jnp.maximum(cnt_t, _EPS)
        pcL = cy_c / jnp.maximum(cnt_c, _EPS)
        ptR = (YT - cy_t) / jnp.maximum(NT - cnt_t, _EPS)
        pcR = (YC - cy_c) / jnp.maximum(NC - cnt_c, _EPS)
        nL = cnt_t + cnt_c
        nR = (NT + NC) - nL
        ntot = jnp.maximum(NT + NC, _EPS)
        d_parent = _divergence(pt_node, pc_node, metric)[:, None, None]
        gain = (
            nL / ntot * _divergence(ptL, pcL, metric)
            + nR / ntot * _divergence(ptR, pcR, metric)
            - d_parent
        )
        # both arms must be represented on both sides (UpliftDRF constraint)
        ok = (cnt_t >= min_rows) & (cnt_c >= min_rows)
        ok &= (NT - cnt_t >= min_rows) & (NC - cnt_c >= min_rows)
        ok &= jnp.arange(nbins)[None, None, :] < nbins - 1
        ok &= active[:, None, None]
        if mtries > 0:
            key, sub = jax.random.split(key)
            keep = jax.random.uniform(sub, (L, F)) < (mtries / F)
            keep = keep.at[:, 0].set(keep[:, 0] | ~keep.any(axis=1))
            ok &= keep[:, :, None]
        gain = jnp.where(ok, gain, -jnp.inf)

        flat = gain.reshape(L, F * nbins)
        best = jnp.argmax(flat, axis=1)
        best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
        bf = (best // nbins).astype(jnp.int32)
        bb = (best % nbins).astype(jnp.int32)
        do_split = best_gain > 1e-10

        pad_edges = jnp.concatenate(
            [edges.astype(jnp.float32), jnp.full((F, 1), jnp.inf, jnp.float32)], axis=1
        )
        bthr = pad_edges[bf, jnp.minimum(bb, nbins - 2)]
        feat_a = feat_a.at[base : base + L].set(jnp.where(do_split, bf, 0))
        bin_a = bin_a.at[base : base + L].set(jnp.where(do_split, bb, 0))
        thr_a = thr_a.at[base : base + L].set(jnp.where(do_split, bthr, 0.0))
        split_a = split_a.at[base : base + L].set(do_split)

        rf = bf[idx]
        rb = bb[idx]
        rcode = jnp.take_along_axis(codes, rf[:, None].astype(jnp.int32), axis=1)[:, 0]
        go_right = (rcode.astype(jnp.int32) > rb) & do_split[idx]
        idx = 2 * idx + go_right.astype(jnp.int32)
        active = jnp.repeat(do_split, 2)

    return treelib.Tree(feat_a, bin_a, thr_a, split_a, value_a)


def auuc(y: np.ndarray, treat: np.ndarray, uplift: np.ndarray, nbins: int = 1000,
         kind: str = "qini"):
    """AUUC over the qini (or gain) curve — ModelMetricsBinomialUplift's
    thresholded cumulative-uplift design."""
    order = np.argsort(-uplift, kind="mergesort")
    y, treat = y[order], treat[order]
    n = len(y)
    cum_t = np.cumsum(treat)
    cum_c = np.cumsum(1 - treat)
    cum_yt = np.cumsum(y * treat)
    cum_yc = np.cumsum(y * (1 - treat))
    ks = np.unique(np.linspace(1, n, min(nbins, n)).astype(np.int64)) - 1
    with np.errstate(divide="ignore", invalid="ignore"):
        if kind == "qini":
            vals = cum_yt[ks] - cum_yc[ks] * np.where(cum_c[ks] > 0, cum_t[ks] / np.maximum(cum_c[ks], 1), 0)
        else:  # gain
            vals = (cum_yt[ks] / np.maximum(cum_t[ks], 1)
                    - cum_yc[ks] / np.maximum(cum_c[ks], 1)) * (ks + 1)
    vals = np.nan_to_num(vals)
    return float(np.trapezoid(vals, ks + 1) / n), (ks + 1, vals)


@dataclass
class ModelMetricsBinomialUplift(ModelMetricsBase):
    auuc: float = float("nan")
    qini: float = float("nan")
    auuc_normalized: float = float("nan")
    ate: float = float("nan")  # average treatment effect of predictions


class UpliftRandomForestModel(H2OModel):
    algo = "upliftdrf"

    def __init__(self, params, x, y, bm, forest, max_depth, domain, treatment_col):
        super().__init__(params)
        self.x = list(x)
        self.y = y
        self.bm = bm
        self.forest = forest  # stacked Tree (ntrees, T)
        self.max_depth = max_depth
        self.domain = domain
        self.treatment_col = treatment_col
        self.ntrees_built = int(forest.feat.shape[0])

    def _uplift(self, frame: Frame) -> np.ndarray:
        X, _, _ = frame_to_matrix(frame, self.x, expected_domains=self.bm.domains)
        s = treelib.predict_forest_raw(self.forest, jnp.asarray(X, jnp.float32),
                                       self.max_depth)
        return np.asarray(s, np.float64) / self.ntrees_built

    def predict(self, test_data: Frame) -> Frame:
        u = self._uplift(test_data)
        # h2o returns uplift_predict + p_y1_ct1/p_y1_ct0 columns
        return Frame.from_dict({"uplift_predict": u})

    def _make_metrics(self, frame: Frame):
        u = self._uplift(frame)
        yv = frame.vec(self.y)
        y = np.asarray(yv.data, np.float64) if yv.type == "enum" else yv.numeric_np()
        tv = frame.vec(self.treatment_col)
        t = np.asarray(tv.data, np.float64) if tv.type == "enum" else tv.numeric_np()
        a_qini, _ = auuc(y, t, u, kind="qini")
        a_gain, _ = auuc(y, t, u, kind="gain")
        return ModelMetricsBinomialUplift(
            nobs=len(y), auuc=a_qini, qini=a_qini,
            auuc_normalized=a_qini / max(np.abs(u).mean(), 1e-12) if len(y) else float("nan"),
            ate=float(u.mean()),
        )


class H2OUpliftRandomForestEstimator(H2OEstimator):
    algo = "upliftdrf"
    _param_defaults = dict(
        treatment_column=None,
        uplift_metric="AUTO",      # AUTO→KL
        auuc_type="AUTO",
        auuc_nbins=-1,
        ntrees=50,
        max_depth=10,
        min_rows=10.0,
        nbins=20,
        sample_rate=0.632,
        mtries=-2,
        col_sample_rate_per_tree=1.0,
    )

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]):
        p = self._parms
        tcol = p.get("treatment_column")
        if not tcol:
            raise ValueError("upliftdrf requires treatment_column")
        x = [c for c in x if c != tcol]
        yvec = train.vec(y)
        if yvec.type != "enum" or yvec.nlevels != 2:
            raise ValueError("upliftdrf requires a binary categorical response")
        tvec = train.vec(tcol)
        treat = (np.asarray(tvec.data, np.float32) if tvec.type == "enum"
                 else tvec.numeric_np().astype(np.float32))
        yarr = np.asarray(yvec.data, np.float32)
        metric = {"AUTO": "KL", "KL": "KL", "Euclidean": "Euclidean",
                  "ChiSquared": "ChiSquared"}[str(p.get("uplift_metric", "AUTO"))]

        X, is_cat, doms = frame_to_matrix(train, x)
        nbins = int(p.get("nbins", 20))
        # pad bins to a power of two like shared_tree does
        B = 1
        while B < nbins + 2:
            B *= 2
        bm = build_bins(X, nbins=B, names=list(x), is_categorical=is_cat,
                        domains=doms, seed=int(self._parms.get("_actual_seed", 1234)))
        F = X.shape[1]
        edges = np.full((F, B - 2), np.inf, np.float32)
        for j, e in enumerate(bm.edges):
            edges[j, : min(len(e), B - 2)] = e[: B - 2]

        n = train.nrow
        codes_d = jnp.asarray(bm.codes)
        y_d = jnp.asarray(yarr)
        edges_d = jnp.asarray(edges)
        sample_rate = float(p.get("sample_rate", 0.632))
        mtries = int(p.get("mtries", -2))
        if mtries in (-1, -2, 0):
            mtries = max(1, int(np.sqrt(F)))
        ntrees = int(p.get("ntrees", 50))
        seed = int(self._parms.get("_actual_seed", 1234))
        rng = np.random.default_rng(seed)

        # all trees dispatched async; ONE stacked D2H at the end (a per-tree
        # np.asarray sync would pay the remote-TPU tunnel RTT ntrees times)
        trees_dev: List = []
        for t in range(ntrees):
            samp = (rng.uniform(size=n) < sample_rate).astype(np.float32)
            wt = jnp.asarray(samp * treat)
            wc = jnp.asarray(samp * (1 - treat))
            tr = build_uplift_tree(
                codes_d, y_d, wt, wc, edges_d,
                max_depth=int(p.get("max_depth", 10)), nbins=B,
                min_rows=float(p.get("min_rows", 10.0)), metric=metric,
                mtries=mtries, key=jax.random.PRNGKey(seed + t),
            )
            trees_dev.append(tr)
        stacked_dev = treelib.stack_trees(trees_dev)
        forest = treelib.Tree(*[np.asarray(f) for f in stacked_dev])

        model = UpliftRandomForestModel(
            self, x, y, bm, forest, int(p.get("max_depth", 10)),
            yvec.domain, tcol,
        )
        model.training_metrics = model._make_metrics(train)
        if valid is not None:
            model.validation_metrics = model._make_metrics(valid)
        return model

    def _cv_predict(self, model, frame: Frame) -> np.ndarray:
        return model._uplift(frame)


UpliftDRF = H2OUpliftRandomForestEstimator
