"""H2OGeneralizedLinearEstimator — GLM.

Reference parity: `h2o-algos/src/main/java/hex/glm/GLM.java` (IRLSM /
L_BFGS / COORDINATE_DESCENT solvers), `hex/glm/GLMTask.java`
(`GLMIterationTask` — the distributed Gram `X'WX` MRTask),
`hex/gram/Gram.java` (Cholesky solve), `hex/DataInfo.java` (standardize /
one-hot — see `model_base.DataInfo`), and the estimator surface
`h2o-py/h2o/estimators/glm.py`. The Airlines-logistic IRLS config is a
BASELINE.json headline.

TPU-first shape of IRLSM: the per-iteration Gram is ONE jitted einsum over
row-sharded X — XLA inserts the `psum` over the ``hosts`` axis automatically
(pjit/GSPMD), which is exactly `GLMIterationTask.reduce()`'s tree-add,
compiled. The tiny (p×p) Cholesky solve happens replicated on-device.
Elastic-net L1 is handled by ISTA (soft-thresholded proximal steps) on the
per-iteration quadratic — the same quadratic COORDINATE_DESCENT minimizes.
Multinomial uses full-batch L-BFGS (optax) on the softmax deviance, the
reference's multinomial L_BFGS path.
"""

from __future__ import annotations

import functools
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import PartitionSpec as P

from ..frame.frame import Frame
from ..parallel import distdata
from ..parallel import mesh as cloudlib
from ..runtime import qos as _qos
from . import estimator_engine as _est
from .metrics import (
    ModelMetricsBinomial,
    ModelMetricsMultinomial,
    ModelMetricsRegression,
)
from .model_base import (SCORE_ROW_BUCKET, DataInfo, H2OEstimator, H2OModel,
                         response_info)

FAMILIES = (
    "AUTO", "gaussian", "binomial", "quasibinomial", "multinomial",
    "poisson", "gamma", "tweedie", "negativebinomial", "ordinal", "fractionalbinomial",
)


# -- link functions (hex/glm/GLMModel.GLMParameters.Link) --------------------
def _linkinv(family: str, eta):
    if family in ("binomial", "quasibinomial", "fractionalbinomial"):
        return jax.nn.sigmoid(eta)
    if family in ("poisson", "gamma", "tweedie", "negativebinomial"):
        return jnp.exp(eta)
    return eta


def _family_deviance_sum(family: str, y, mu, w, tweedie_p=1.5, xp=jnp):
    """Σ w·d(y,μ) with the per-family unit deviance d — the quantity lambda
    search minimizes (hex/glm/GLMModel.GLMParameters.deviance per family;
    squared error only for gaussian). `xp` is jnp (device path) or np (host
    f64 path)."""
    if family in ("binomial", "quasibinomial", "fractionalbinomial"):
        mu_c = xp.clip(mu, 1e-15, 1 - 1e-15)
        return -2.0 * xp.sum(w * (y * xp.log(mu_c)
                                  + (1 - y) * xp.log(1 - mu_c)))
    if family == "poisson":
        mu_c = xp.clip(mu, 1e-10, None)
        ylogy = xp.where(y > 0, y * xp.log(xp.clip(y, 1e-10, None) / mu_c), 0.0)
        return 2.0 * xp.sum(w * (ylogy - (y - mu_c)))
    if family == "gamma":
        mu_c = xp.clip(mu, 1e-10, None)
        y_c = xp.clip(y, 1e-10, None)
        return 2.0 * xp.sum(w * (-xp.log(y_c / mu_c) + (y - mu_c) / mu_c))
    if family == "tweedie":
        p = float(tweedie_p)
        if abs(p - 1.0) < 1e-8:     # limit form: poisson deviance
            return _family_deviance_sum("poisson", y, mu, w, xp=xp)
        if abs(p - 2.0) < 1e-8:     # limit form: gamma deviance
            return _family_deviance_sum("gamma", y, mu, w, xp=xp)
        mu_c = xp.clip(mu, 1e-10, None)
        y_c = xp.clip(y, 0.0, None)
        return 2.0 * xp.sum(w * (
            y_c ** (2 - p) / ((1 - p) * (2 - p))
            - y_c * mu_c ** (1 - p) / (1 - p)
            + mu_c ** (2 - p) / (2 - p)))
    return xp.sum(w * (y - mu) ** 2)


def _irls_weights(family: str, eta, mu, y, tweedie_p=1.5):
    """(W, z): working weights and response for one IRLS iteration."""
    if family in ("binomial", "quasibinomial", "fractionalbinomial"):
        W = jnp.clip(mu * (1 - mu), 1e-10, None)
        z = eta + (y - mu) / W
    elif family == "poisson":
        W = jnp.clip(mu, 1e-10, None)
        z = eta + (y - mu) / W
    elif family == "gamma":
        W = jnp.ones_like(mu)
        z = eta + (y - mu) / jnp.clip(mu, 1e-10, None)
    elif family == "tweedie":
        W = jnp.clip(mu ** (2 - tweedie_p), 1e-10, None)
        z = eta + (y - mu) / jnp.clip(mu, 1e-10, None)  # log link
    else:  # gaussian
        W = jnp.ones_like(mu)
        z = y
    return W, z


@jax.jit
def _wsums(y, w):
    """(Σw, Σw·y) as replicated device scalars — safe on sharded inputs."""
    return jnp.sum(w), jnp.sum(w * y)


@functools.partial(jax.jit, static_argnames=("family", "tweedie_p"))
def _deviance_device(X, y, w, beta, family: str, tweedie_p: float):
    eta = jnp.matmul(X, beta, precision=jax.lax.Precision.HIGHEST)
    mu = _linkinv(family, eta)
    return _family_deviance_sum(family, y, mu, w, tweedie_p)


@functools.partial(jax.jit, static_argnames=("family", "tweedie_p"))
def _pearson_sums(X, y, w, beta, family: str, tweedie_p: float):
    """(Σ w·(y−μ)²/V(μ), Σw) — the Pearson X² pieces of the dispersion
    estimate as jit-global reductions (safe on row-sharded X)."""
    eta = jnp.matmul(X, beta, precision=jax.lax.Precision.HIGHEST)
    mu = _linkinv(family, eta)
    if family == "gamma":
        vfun = jnp.maximum(mu, 1e-12) ** 2
    elif family == "tweedie":
        vfun = jnp.maximum(mu, 1e-12) ** tweedie_p
    else:
        vfun = jnp.ones_like(mu)
    return jnp.sum(w * (y - mu) ** 2 / vfun), jnp.sum(w)


@functools.partial(jax.jit, static_argnames=("family",))
def _gram_step(X, y, w, beta, family: str, tweedie_p: float = 1.5):
    """One GLMIterationTask: distributed Gram X'WX and X'Wz (+ psum by XLA
    when X is row-sharded)."""
    eta = X @ beta
    mu = _linkinv(family, eta)
    W, z = _irls_weights(family, eta, mu, y, tweedie_p)
    Ww = W * w
    gram = jnp.einsum("np,n,nq->pq", X, Ww, X)
    xy = jnp.einsum("np,n->p", X, Ww * z)
    return gram, xy


def _solve_pen_device(gram, xy, lam, alpha, n_obs, pen_mask, beta_prev,
                      non_negative: bool):
    """Penalized IRLS-quadratic solve ON DEVICE — Cholesky for ridge,
    500-step projected ISTA when l1>0 or non_negative (the same quadratic
    COORDINATE_DESCENT iterates on). Shared by the lambda-path program and
    the fused single-lambda IRLS loop so the two can never drift."""
    pdim = gram.shape[0]
    l2 = lam * (1.0 - alpha) * n_obs
    l1 = lam * alpha * n_obs
    A = gram + jnp.diag(pen_mask * l2)

    def ridge(_):
        return jnp.linalg.solve(
            A + 1e-6 * jnp.eye(pdim, dtype=jnp.float32), xy)

    def ista(_):
        L = jnp.linalg.eigvalsh(A)[-1] + 1e-8
        thr = l1 / L * pen_mask

        def body(i, b):
            b_new = b - (A @ b - xy) / L
            b_new = jnp.sign(b_new) * jnp.maximum(
                jnp.abs(b_new) - thr, 0.0)
            if non_negative:
                b_new = b_new.at[:pdim - 1].set(
                    jnp.maximum(b_new[:pdim - 1], 0.0))
            return b_new

        return jax.lax.fori_loop(0, 500, body, beta_prev)

    return jax.lax.cond((l1 > 0) | non_negative, ista, ridge, None)


@functools.partial(jax.jit, static_argnames=("family", "max_iter",
                                              "non_negative", "tweedie_p"))
def _glm_path_device(X, y, w, Xe, ye, we, lams, alpha, n_obs, beta0,
                     beta_eps, tweedie_p, family: str, max_iter: int,
                     non_negative: bool):
    """The WHOLE elastic-net regularization path as one XLA program.

    lax.scan over λ (warm-started), lax.while_loop IRLS per λ, penalized
    solve on device (Cholesky for ridge, 500-step projected ISTA when
    l1>0), deviance evaluated against (Xe, ye, we) — the validation set
    when given, else training. Replaces ~nlambda·iters host round-trips
    (gram D2H + host solve each) with ONE dispatch; the caller re-solves
    the chosen λ on host in f64 for the reported coefficients
    (hex/glm/GLM.java lambda search, computeSubmodel loop). For gaussian
    the IRLS weights don't depend on β, so the Gram/xy are computed ONCE
    and reused across the whole path (ISSUE 15 warm-start contract) —
    same values every iteration recomputed before, at ~1/iters the
    einsum cost."""
    pdim = X.shape[1]
    pen_mask = jnp.ones(pdim, jnp.float32).at[pdim - 1].set(0.0)

    def solve_pen(gram, xy, lam, beta_prev):
        return _solve_pen_device(gram, xy, lam, alpha, n_obs, pen_mask,
                                 beta_prev, non_negative)

    if family == "gaussian":
        Wg = jnp.ones_like(y) * w
        gram_g = jnp.einsum("np,n,nq->pq", X, Wg, X,
                            precision=jax.lax.Precision.HIGHEST)
        xy_g = jnp.einsum("np,n->p", X, Wg * y,
                          precision=jax.lax.Precision.HIGHEST)

    def deviance(beta):
        eta = jnp.matmul(Xe, beta, precision=jax.lax.Precision.HIGHEST)
        mu = _linkinv(family, eta)
        return _family_deviance_sum(family, ye, mu, we, tweedie_p)

    def fit_one(beta, lam):
        def cond(state):
            it, b, delta = state
            return (it < max_iter) & (delta >= beta_eps)

        def body(state):
            it, b, _ = state
            if family == "gaussian":
                gram, xy = gram_g, xy_g
            else:
                eta = jnp.matmul(X, b, precision=jax.lax.Precision.HIGHEST)
                mu = _linkinv(family, eta)
                W, z = _irls_weights(family, eta, mu, y, tweedie_p)
                Ww = W * w
                gram = jnp.einsum("np,n,nq->pq", X, Ww, X,
                                  precision=jax.lax.Precision.HIGHEST)
                xy = jnp.einsum("np,n->p", X, Ww * z,
                                precision=jax.lax.Precision.HIGHEST)
            nb = solve_pen(gram, xy, lam, b)
            return it + 1, nb, jnp.max(jnp.abs(nb - b))

        _, beta, _ = jax.lax.while_loop(
            cond, body, (jnp.int32(0), beta, jnp.float32(jnp.inf)))
        # f32 divergence guard: a non-finite β would NaN-poison the
        # warm-start carry for every later λ — reset instead, and report
        # +inf deviance so this λ can never be selected
        ok = jnp.isfinite(beta).all()
        beta = jnp.where(ok, beta, jnp.zeros_like(beta))
        dev = jnp.where(ok, deviance(beta), jnp.float32(jnp.inf))
        return beta, (beta, dev)

    _, (betas, devs) = jax.lax.scan(fit_one, beta0, lams)
    return betas, devs


def _irls_device_fn(cloud, shard_mode: str, n_shards: int, family: str,
                    non_negative: bool, one_step: bool):
    """The fused single-λ IRLS fit as ONE device program (ISSUE 15):
    `lax.while_loop` with the convergence test (max|Δβ| < β_eps) ON
    DEVICE — the host reads only the final (β, iterations, Δ) triple,
    replacing the per-iteration gram D2H + host solve round-trip.

    Row reductions (the Gram X'WX and X'Wz) run as `local_blocks` ordered
    block partials merged by `ordered_axis_fold` under the shard plan —
    mesh-sharded on a multi-device cloud, the same blocked structure
    forced on one device — so an N-device IRLS fit is bit-identical to
    the 1-device forced-shard lane (the PR 9 contract). `one_step` marks
    gaussian with α·λ = 0, whose single solve mirrors the host loop's
    unconditional gaussian break — including under non_negative, where
    both paths do exactly one projected-ISTA pass; plain gaussian hoists
    the β-independent Gram out of the loop. Cached per cloud via the
    engine program cache."""
    local_blocks, axis = _est.local_plan(cloud, shard_mode, n_shards)
    key = ("glm_irls", family, local_blocks, axis, bool(non_negative),
           bool(one_step))

    def build():
        # carry (it, beta, delta) enters as traced arguments and cond gains
        # `it < stop_at`, so the QoS gate can run the fit as a resumable
        # sequence of bounded segments (est.segment_stops) — stop_at =
        # max_iter is the single-dispatch identity (same trip count, same
        # body, same bits; pinned). The gaussian Gram hoist is β-independent,
        # so recomputing it per segment is also bit-identical.
        def inner(X, y, w, beta0, it0, delta0, lam, alpha, n_obs, max_iter,
                  stop_at, beta_eps, tweedie_p):
            pdim = X.shape[1]
            pen_mask = jnp.ones(pdim, jnp.float32).at[pdim - 1].set(0.0)

            def gram_xy(b):
                eta = X @ b
                mu = _linkinv(family, eta)
                W, z = _irls_weights(family, eta, mu, y, tweedie_p)
                Ww = W * w
                if local_blocks:
                    # ONE augmented gemm per block — (WwX)' @ [X | z]
                    # yields gram AND xy from the same dot: the
                    # gemm-shaped form lowers identically inside a lane's
                    # shard_map body and inside the S-block single-device
                    # program (a separate gemv for xy did NOT — its
                    # accumulation fused differently per context), which
                    # is what makes blocks==mesh bit-identical
                    Xw = X * Ww[:, None]
                    Xz = jnp.concatenate([X, z[:, None]], axis=1)
                    sl = _est.block_slices(X.shape[0], local_blocks)
                    gz = _est.fold_blocks(
                        jnp.stack([Xw[s].T @ Xz[s] for s in sl]), axis)
                    return gz[:, :-1], gz[:, -1]
                return (jnp.einsum("np,n,nq->pq", X, Ww, X),
                        jnp.einsum("np,n->p", X, Ww * z))

            def solve(gram, xy, bprev):
                return _solve_pen_device(gram, xy, lam, alpha, n_obs,
                                         pen_mask, bprev, non_negative)

            if one_step or family == "gaussian":
                gram_g, xy_g = gram_xy(beta0)   # gaussian: β-independent
            if one_step:
                beta = solve(gram_g, xy_g, beta0)
                return beta, jnp.int32(1), jnp.max(jnp.abs(beta - beta0))

            def cond(state):
                it, b, delta = state
                return (it < max_iter) & (delta >= beta_eps) & (it < stop_at)

            def body(state):
                it, b, _ = state
                gram, xy = ((gram_g, xy_g) if family == "gaussian"
                            else gram_xy(b))
                nb = solve(gram, xy, b)
                return it + 1, nb, jnp.max(jnp.abs(nb - b))

            it, beta, delta = jax.lax.while_loop(
                cond, body, (it0, beta0, delta0))
            return beta, it, delta

        if axis is not None:
            rspec = P(cloudlib.ROWS_AXIS)
            rep = P()
            inner = cloudlib.shard_call(
                inner, cloud,
                in_specs=(rspec, rspec, rspec) + (rep,) * 10,
                out_specs=(rep, rep, rep), check_rep=False)
        return jax.jit(inner)

    return _est.cached_program(cloud, key, build)


def _solve_penalized(gram, xy, lam, alpha, n_obs, intercept_idx, beta0,
                     non_negative=False):
    """Solve the IRLS quadratic with elastic-net penalty (host, p×p).

    Ridge part closed-form via Cholesky; L1 (and the non_negative
    constraint, used by the StackedEnsemble metalearner) via projected ISTA
    on the quadratic — the same subproblem hex/glm COORDINATE_DESCENT
    iterates on."""
    p = gram.shape[0]
    pen_mask = np.ones(p)
    pen_mask[intercept_idx] = 0.0  # intercept is never penalized
    l2 = lam * (1 - alpha) * n_obs
    l1 = lam * alpha * n_obs
    A = gram + np.diag(pen_mask * l2)
    if l1 == 0 and not non_negative:
        try:
            return np.linalg.solve(A + 1e-8 * np.eye(p), xy)
        except np.linalg.LinAlgError:
            return np.linalg.lstsq(A, xy, rcond=None)[0]
    # (projected) ISTA
    L = np.linalg.eigvalsh(A).max() + 1e-8
    b = beta0.copy()
    for _ in range(500):
        grad = A @ b - xy
        b_new = b - grad / L
        thr = l1 / L * pen_mask
        b_new = np.sign(b_new) * np.maximum(np.abs(b_new) - thr, 0)
        if non_negative:
            b_new[:intercept_idx] = np.maximum(b_new[:intercept_idx], 0.0)
        if np.max(np.abs(b_new - b)) < 1e-9:
            b = b_new
            break
        b = b_new
    return b


def attach_linear_artifacts(model: "GLMModel", train, valid, Xd,
                            cloud_size: int, n: int) -> "GLMModel":
    """Training/validation metrics + |coefficient| varimp for a fitted
    linear model — shared by GLM and the XGBoost gblinear booster.

    Reuses the training design matrix already in HBM for training metrics —
    single-device only: a row-sharded Xd may span non-addressable devices
    (multi-host mesh) and padded tail rows would corrupt metrics."""
    model.training_metrics = model._make_metrics(
        train, Xd=Xd if (cloud_size == 1 and int(Xd.shape[0]) == n) else None)
    if valid is not None:
        model.validation_metrics = model._make_metrics(valid)
    # GLM varimp = |standardized coefficient| magnitudes
    beta = model.beta
    b = np.asarray(beta if model.family != "multinomial"
                   else np.abs(beta).mean(axis=0))
    mags = np.abs(b[:-1])
    if mags.sum() > 0:
        order = np.argsort(-mags)
        model.varimp_table = [
            (model.dinfo.coef_names[i], float(mags[i]),
             float(mags[i] / mags.max()), float(mags[i] / mags.sum()))
            for i in order if mags[i] > 0]
    return model


class GLMModel(H2OModel):
    algo = "glm"

    def __init__(self, params, x, y, dinfo: DataInfo, family, beta, domain,
                 lambda_best=0.0, stderr=None, full_path=None):
        super().__init__(params)
        self.x = list(x)
        self.y = y
        self.dinfo = dinfo
        self.family = family
        self.beta = beta  # (p+1,) with intercept last, or (K, p+1) multinomial
        self.domain = domain
        self.lambda_best = lambda_best
        self.stderr = stderr
        self.full_path = full_path  # lambda-search path [(lam, beta), ...]

    def _names(self) -> List[str]:
        return self.dinfo.coef_names + ["Intercept"]

    def coef(self) -> Dict[str, float]:
        """De-standardized coefficients (GLMModel.coefficients)."""
        if self.family == "multinomial":
            return {
                f"{cls}": dict(zip(self._names(), self._destandardize(self.beta[k])))
                for k, cls in enumerate(self.domain)
            }
        return dict(zip(self._names(), self._destandardize(self.beta)))

    def coef_norm(self) -> Dict[str, float]:
        if self.family == "multinomial":
            return {
                f"{cls}": dict(zip(self._names(), np.asarray(self.beta[k])))
                for k, cls in enumerate(self.domain)
            }
        return dict(zip(self._names(), np.asarray(self.beta)))

    def summary(self):
        s = super().summary()
        # intercepts excluded; a multinomial predictor counts once if active
        # in ANY class (matches the total's per-predictor granularity)
        if self.family == "multinomial":
            slopes = np.abs(np.asarray(self.beta)[:, :-1]).max(axis=0)
        else:
            slopes = np.abs(np.asarray(self.beta)[:-1])
        s.update(family=self.family,
                 number_of_predictors_total=len(self.dinfo.coef_names),
                 number_of_active_predictors=int((slopes > 1e-10).sum()),
                 lambda_=self.lambda_best)
        return s

    def coef_with_p_values(self):
        """Coefficient table with std errors / z / p-values on the DATA scale
        (matches coef()) — requires compute_p_values=True and lambda=0
        (GLMModel p-value output)."""
        if self.stderr is None:
            raise ValueError(
                "p-values unavailable: train with compute_p_values=True "
                "and lambda_=0")
        b = np.asarray(self.beta, np.float64)
        pdim = len(b) - 1
        cov = getattr(self, "covmat", None)
        if self.dinfo.standardize and self.dinfo.means is not None and cov is not None:
            # affine destandardization T: slope_j /= σ_j, intercept absorbs
            # −Σ β_j μ_j/σ_j; covariance transforms as T Cov Tᵀ
            T = np.zeros((pdim + 1, pdim + 1))
            T[np.arange(pdim), np.arange(pdim)] = 1.0 / self.dinfo.stds
            T[pdim, :pdim] = -self.dinfo.means / self.dinfo.stds
            T[pdim, pdim] = 1.0
            b = T @ b
            se = np.sqrt(np.maximum(np.diag(T @ cov @ T.T), 0.0))
        else:
            b = self._destandardize(b)
            se = np.asarray(self.stderr, np.float64)
        z = b / np.maximum(se, 1e-300)
        # two-sided normal p-value (the reference uses z-tests for binomial)
        from math import erfc, sqrt

        pv = [erfc(abs(zz) / sqrt(2.0)) for zz in z]
        return [
            dict(names=n, coefficients=float(bb), std_error=float(s),
                 z_value=float(zz), p_value=float(p))
            for n, bb, s, zz, p in zip(self._names(), b, se, z, pv)
        ]

    def _destandardize(self, b):
        b = np.asarray(b, np.float64)
        if not self.dinfo.standardize or self.dinfo.means is None:
            return b
        out = b.copy()
        out[:-1] = b[:-1] / self.dinfo.stds
        out[-1] = b[-1] - float((b[:-1] * self.dinfo.means / self.dinfo.stds).sum())
        return out

    def _eta_dev(self, frame: Frame, Xd=None):
        """Linear predictor as a DEVICE array. Expansion + matvec run on
        device (compact upload, see DataInfo.device_design); `Xd` lets the
        training loop reuse its HBM design matrix for training metrics.
        HIGHEST matmul precision keeps f32 logits exact (the TPU default
        truncates matmul operands to bf16)."""
        if Xd is None:
            # row-bucketed scoring design: CV folds / paged frames of
            # nearby sizes share one expand + one matmul program. The
            # result may carry up to 511 PAD ROWS — callers slice to
            # frame.nrow on the HOST after materializing (a device-side
            # slice would reintroduce one tiny program per exact size,
            # defeating the bucket)
            Xd = self.dinfo.device_design(frame, fit=False,
                                          add_intercept=True,
                                          row_bucket=SCORE_ROW_BUCKET)
        beta = jnp.asarray(np.asarray(self.beta, np.float32))
        return jnp.matmul(Xd, beta.T, precision=jax.lax.Precision.HIGHEST)

    def _eta(self, frame: Frame, Xd=None) -> np.ndarray:
        # host-side slice drops any row-bucket pad (see _eta_dev)
        return np.asarray(self._eta_dev(frame, Xd=Xd),
                          np.float64)[: frame.nrow]

    def _score(self, frame: Frame, Xd=None) -> np.ndarray:
        # link inverse applied on device: ONE n-sized transfer per scoring;
        # the host-side slice drops any row-bucket pad (see _eta_dev)
        eta = self._eta_dev(frame, Xd=Xd)
        if self.family == "multinomial":
            return np.asarray(jax.nn.softmax(eta, axis=1),
                              np.float64)[: frame.nrow]
        return np.asarray(_linkinv(self.family, eta),
                          np.float64)[: frame.nrow]

    def predict(self, test_data: Frame) -> Frame:
        out = self._score(test_data)
        if self.family in ("binomial", "quasibinomial"):
            p1 = out
            d = {"predict": np.asarray(self.domain, dtype=object)[(p1 > 0.5).astype(int)],
                 str(self.domain[0]): 1 - p1, str(self.domain[1]): p1}
            return Frame.from_dict(d, column_types={"predict": "enum"})
        if self.family == "multinomial":
            lab = out.argmax(axis=1)
            d = {"predict": np.asarray(self.domain, dtype=object)[lab]}
            for i, cls in enumerate(self.domain):
                d[str(cls)] = out[:, i]
            return Frame.from_dict(d, column_types={"predict": "enum"})
        return Frame.from_dict({"predict": out})

    def _make_metrics(self, frame: Frame, Xd=None):
        out = self._score(frame, Xd=Xd)
        yv = frame.vec(self.y)
        if self.family in ("binomial", "quasibinomial"):
            return ModelMetricsBinomial.make(np.asarray(yv.data), out)
        if self.family == "multinomial":
            return ModelMetricsMultinomial.make(np.asarray(yv.data), out)
        return ModelMetricsRegression.make(yv.numeric_np(), out)


class H2OGeneralizedLinearEstimator(H2OEstimator):
    algo = "glm"
    _param_defaults = dict(
        family="AUTO",
        solver="AUTO",
        alpha=None,
        lambda_=None,
        lambda_search=False,
        nlambdas=-1,
        lambda_min_ratio=-1.0,
        standardize=True,
        intercept=True,
        non_negative=False,
        max_iterations=-1,
        beta_epsilon=1e-4,
        objective_epsilon=-1.0,
        gradient_epsilon=-1.0,
        link="family_default",
        tweedie_variance_power=0.0,
        tweedie_link_power=1.0,
        theta=1e-10,
        missing_values_handling="MeanImputation",
        compute_p_values=False,
        remove_collinear_columns=False,
        balance_classes=False,
        class_sampling_factors=None,
        max_after_balance_size=5.0,
        prior=-1.0,
        cold_start=False,
        interactions=None,
        beta_constraints=None,
    )

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]) -> GLMModel:
        p = self._parms
        yvec = train.vec(y)
        problem, nclass, domain = response_info(yvec)
        family = p.get("family", "AUTO")
        if family not in FAMILIES:
            raise ValueError(f"family {family!r}: expected one of {FAMILIES}")
        for av in np.atleast_1d(np.asarray(p.get("alpha")
                                           if p.get("alpha") is not None
                                           else 0.5, np.float64)):
            if not (0.0 <= av <= 1.0):
                raise ValueError(f"alpha must be in [0, 1], got {av}")
        lam = p.get("lambda_")
        if lam is not None:
            for lv in np.atleast_1d(np.asarray(lam, np.float64)):
                if lv < 0:
                    raise ValueError(f"lambda must be >= 0, got {lv}")
        if family == "AUTO":
            family = {"binomial": "binomial", "multinomial": "multinomial"}.get(
                problem, "gaussian"
            )
        std_flag = bool(p.get("standardize", True))
        n = train.nrow
        w = (
            train.vec(p["weights_column"]).numeric_np()
            if p.get("weights_column")
            else np.ones(n)
        ).astype(np.float32)

        if family in ("binomial", "quasibinomial", "fractionalbinomial"):
            yarr = np.asarray(yvec.data, np.float32) if yvec.type == "enum" else yvec.numeric_np().astype(np.float32)
        elif family == "multinomial":
            yarr = np.asarray(yvec.data, np.int32)
        else:
            yarr = yvec.numeric_np().astype(np.float32)

        alpha = p.get("alpha")
        alpha = float(alpha[0] if isinstance(alpha, (list, tuple)) else (alpha if alpha is not None else 0.5))
        lam = p.get("lambda_")
        lambda_search = bool(p.get("lambda_search"))
        tweedie_p = float(p.get("tweedie_variance_power") or 1.5)
        max_iter = int(p.get("max_iterations", -1))
        if max_iter <= 0:
            max_iter = 50
        beta_eps = float(p.get("beta_epsilon", 1e-4))

        cloud = cloudlib.cloud()
        multiproc = distdata.multiprocess()
        # -- estimator-engine dispatch (ISSUE 15 / ISSUE 18) ------------------
        # engine on: cached standardized design (one upload per sweep) +
        # fused whole-fit IRLS; gated off for the exotic corners — legacy
        # comparator and the mesh path for multinomial / degenerate row
        # counts. Multi-process clouds run the pod mesh lane (ISSUE 18:
        # canonical global layout, blocked Gram fold over the pod mesh)
        # for plain single-λ fits; lambda_search and multinomial keep the
        # pre-engine multi-process paths.
        engine_on = not _est.legacy() and not multiproc
        shard_mode, n_shards = (_est.shard_plan(cloud.size, multiproc)
                                if (engine_on or multiproc) else ("off", 0))
        n_glob = n
        if multiproc:
            n_glob = int(getattr(train, "dist").global_nrow
                         if getattr(train, "dist", None) else
                         distdata.global_sum(np.asarray([n]))[0])
        if shard_mode == "mesh" and (n_glob < cloud.size
                                     or family == "multinomial"
                                     or (multiproc and lambda_search)):
            shard_mode, n_shards = "off", 0
        pod = multiproc and shard_mode == "mesh"
        use_cached_design = engine_on and (cloud.size == 1
                                           or shard_mode == "mesh")
        y_host_fit, w_host_fit = yarr, w
        cache0 = None
        if use_cached_design:
            from . import dataset_cache as _dc

            cache0 = _dc.snapshot()
        yd = jnp.asarray(yarr if family != "multinomial" else yarr.astype(np.float32))
        wd = jnp.asarray(w)
        if multiproc:
            dinfo = DataInfo(train, x, standardize=std_flag)
            # multi-host cloud: this process holds only its ingest shard —
            # assemble global row-sharded arrays homed where the data was
            # parsed (MRTask compute-where-the-chunks-live), zero-weight
            # padding balancing unequal byte ranges
            X = dinfo.fit_transform(train)      # standardization stats are
            #                                     global (DataInfo collective)
            Xi = np.concatenate([X, np.ones((n, 1), np.float32)], axis=1)
            y_f32 = np.asarray(yarr, np.float32)
            if pod:
                # ISSUE 18 pod lane: relayout the ingest shards onto the
                # CANONICAL padded grid the 1-device forced-shard
                # comparator uses (pad_rows(n_global, S), all pad at the
                # global tail), so the blocked Gram fold groups identical
                # f32 partials in the identical order — bit-identical β.
                # Rows move only at slice boundaries (exchange_rows); no
                # rank ever materializes the global design matrix.
                _counts = distdata.row_counts(n)
                npad = _est.pad_rows(n_glob, n_shards)
                quota = npad // jax.process_count()
                Xd = distdata.global_row_array(
                    distdata.to_canonical(Xi.astype(np.float32), npad,
                                          counts=_counts), quota, cloud)
                yd = distdata.global_row_array(
                    distdata.to_canonical(y_f32, npad, counts=_counts),
                    quota, cloud)
                wd = distdata.global_row_array(
                    distdata.to_canonical(w, npad, counts=_counts),
                    quota, cloud)
                # exact global response/weight columns (rank order =
                # global ingest order) for the host f64 β₀ init sums — a
                # psum of per-rank partials would not be bitwise the
                # comparator's single np.sum
                y_host_fit = distdata.allgather_rows(y_f32)
                w_host_fit = distdata.allgather_rows(w)
            else:
                quota = distdata.local_quota(n)
                Xd = distdata.global_row_array(
                    Xi.astype(np.float32), quota, cloud)
                yd = distdata.global_row_array(y_f32, quota, cloud)
                wd = distdata.global_row_array(w, quota, cloud)
            n = n_glob
        elif use_cached_design:
            ndev_eff = cloud.size if shard_mode == "mesh" else 1
            dinfo, Xd = _est.design_matrix(
                train, x, standardize=std_flag, add_intercept=True,
                n_shards=n_shards, n_devices=ndev_eff)
            npad = int(Xd.shape[0])
            if npad != n or ndev_eff > 1:
                ypad = np.concatenate([np.asarray(
                    yarr if family != "multinomial"
                    else yarr.astype(np.float32), np.float32),
                    np.zeros(npad - n, np.float32)])
                wpad = np.concatenate([w, np.zeros(npad - n, np.float32)])
                if ndev_eff > 1:
                    rs = cloud.row_sharding()
                    yd = jax.device_put(jnp.asarray(ypad), rs)
                    wd = jax.device_put(jnp.asarray(wpad), rs)
                else:
                    yd, wd = jnp.asarray(ypad), jnp.asarray(wpad)
        elif cloud.size > 1 and n >= cloud.size:
            dinfo = DataInfo(train, x, standardize=std_flag)
            X = dinfo.fit_transform(train)
            Xi = np.concatenate([X, np.ones((n, 1), np.float32)], axis=1)
            npad = cloudlib.pad_to_multiple(n, cloud.size)
            padn = npad - n
            Xd = jnp.asarray(np.concatenate([Xi, np.zeros((padn, Xi.shape[1]), np.float32)]))
            yd = jnp.asarray(np.concatenate([np.asarray(yd), np.zeros(padn, np.float32)]))
            wd = jnp.asarray(np.concatenate([w, np.zeros(padn, np.float32)]))
            rs = cloud.row_sharding()
            Xd, yd, wd = jax.device_put(Xd, rs), jax.device_put(yd, rs), jax.device_put(wd, rs)
        else:
            # compact upload + on-device one-hot expansion (the dense design
            # matrix never crosses the host↔device link)
            dinfo = DataInfo(train, x, standardize=std_flag)
            Xd = dinfo.device_design(train, fit=True, add_intercept=True)
        nfeat = len(dinfo.coef_names)
        fitplan: Dict[str, object] = dict(path="legacy")

        full_path = None
        stderr = None
        cov = None
        if family == "multinomial":
            beta = self._fit_multinomial(Xd, yarr, wd, nclass, alpha,
                                         lam or 0.0, max_iter, n_global=n)
            lam_best = lam or 0.0
        else:
            if lambda_search:
                vdata = None
                if valid is not None:
                    Xv = dinfo.transform(valid)
                    Xvi = np.concatenate(
                        [Xv, np.ones((Xv.shape[0], 1), np.float32)], axis=1)
                    yvv = valid.vec(y)
                    if yvv.type == "enum":
                        codes_v = np.asarray(yvv.data, np.int64)
                        if yvv.domain != domain and yvv.domain:
                            # remap to the TRAINING response domain
                            lookup = {d: i for i, d in enumerate(domain or [])}
                            remap = np.asarray(
                                [lookup.get(d, -1) for d in yvv.domain], np.int64)
                            codes_v = np.where(codes_v >= 0,
                                               remap[np.maximum(codes_v, 0)], -1)
                        yva = codes_v.astype(np.float32)
                    else:
                        yva = yvv.numeric_np().astype(np.float32)
                    wv = (valid.vec(p["weights_column"]).numeric_np()
                          if p.get("weights_column")
                          and p["weights_column"] in valid.names
                          else np.ones(Xv.shape[0])).astype(np.float32)
                    if distdata.multiprocess():
                        # each process holds its valid shard; zero-weight
                        # pads drop out of the (jit-global) deviance sums,
                        # so lambda selection is consistent on every rank
                        quota_v = distdata.local_quota(Xv.shape[0])
                        vdata = (
                            distdata.global_row_array(
                                Xvi.astype(np.float32), quota_v, cloud),
                            distdata.global_row_array(
                                yva.astype(np.float32), quota_v, cloud),
                            distdata.global_row_array(
                                wv.astype(np.float32), quota_v, cloud),
                        )
                    else:
                        vdata = (jnp.asarray(Xvi), jnp.asarray(yva),
                                 jnp.asarray(wv))
                beta, lam_best, full_path = self._lambda_path(
                    Xd, yd, wd, family, alpha, n, nfeat, max_iter, beta_eps,
                    tweedie_p, p, vdata=vdata, fitplan=fitplan,
                )
            else:
                lam_v = float(lam[0] if isinstance(lam, (list, tuple)) else (lam or 0.0))
                if engine_on or pod:
                    beta = self._irls_fused(
                        Xd, yd, wd, family, lam_v, alpha, max_iter,
                        beta_eps, tweedie_p, cloud, shard_mode, n_shards,
                        fitplan, y_host=y_host_fit, w_host=w_host_fit)
                else:
                    beta = self._irls(Xd, yd, wd, family, lam_v, alpha, max_iter, beta_eps, tweedie_p)
                lam_best = lam_v
            if p.get("compute_p_values") and (lam_best == 0):
                gram, _ = _gram_step(Xd, yd, wd, jnp.asarray(beta), family, tweedie_p)
                try:
                    # the Gram comes out of the jit replicated on every rank,
                    # so the inverse/dispersion below agree across processes
                    cov = np.linalg.inv(np.asarray(gram, np.float64))
                    # dispersion: Pearson X²/(n−p) for the families whose
                    # variance is estimated (gaussian/gamma/tweedie); fixed
                    # at 1 for binomial/poisson (GLM dispersion_estimated)
                    if family in ("gaussian", "gamma", "tweedie") \
                            and distdata.multiprocess():
                        # jit-global Pearson sums — the sharded Xd never
                        # reaches the host; f32 accumulation, like the Gram
                        x2, wsum = _pearson_sums(
                            Xd, yd, wd, jnp.asarray(beta, jnp.float32),
                            family, float(tweedie_p))
                        dof = max(float(wsum) - Xd.shape[1], 1.0)
                        dispersion = float(x2) / dof
                    elif family in ("gaussian", "gamma", "tweedie"):
                        eta = np.asarray(Xd @ jnp.asarray(beta, jnp.float32), np.float64)
                        mu = np.asarray(_linkinv(family, jnp.asarray(eta)), np.float64)
                        yv_ = np.asarray(yd, np.float64)
                        wv_ = np.asarray(wd, np.float64)
                        vfun = {"gaussian": np.ones_like(mu),
                                "gamma": np.maximum(mu, 1e-12) ** 2,
                                "tweedie": np.maximum(mu, 1e-12) ** tweedie_p}[family]
                        dof = max(float(wv_.sum()) - Xd.shape[1], 1.0)
                        dispersion = float(np.sum(wv_ * (yv_ - mu) ** 2 / vfun) / dof)
                    else:
                        dispersion = 1.0
                    cov = cov * dispersion
                    stderr = np.sqrt(np.maximum(np.diag(cov), 0.0))
                except np.linalg.LinAlgError:
                    cov = None
                    stderr = None

        _est.record_fit(
            "glm", str(fitplan.get("path", "legacy")),
            iterations=fitplan.get("iterations"),
            converged=fitplan.get("converged"),
            matrix_cache=(_est.matrix_cache_state(cache0)
                          if cache0 is not None else None),
            # the λ-path program ("fused_path") runs plain full-row
            # einsums — only the blocked IRLS paths really sharded
            n_shards=n_shards if fitplan.get("path") in (
                "fused", "fused_blocks", "fused_mesh") else 0,
            n_devices=cloud.size if shard_mode == "mesh" else 1,
            family=family)
        model = GLMModel(self, x, y, dinfo, family, beta, domain,
                         lambda_best=lam_best, stderr=stderr, full_path=full_path)
        model.covmat = cov  # (p+1)² dispersion-scaled covariance (p-values)
        return attach_linear_artifacts(model, train, valid, Xd, cloud.size, n)

    @staticmethod
    def _beta_from_sums(wy: float, n_obs: float, family: str,
                        pdim: int) -> np.ndarray:
        """β₀ with the family's intercept warm start from (Σw·y, Σw) — the
        ONE copy of the formula; host-loop and fused inits both call it so
        they can never desynchronize."""
        beta = np.zeros(pdim, np.float64)
        if family in ("binomial", "quasibinomial", "fractionalbinomial"):
            mu0 = wy / (n_obs + 1e-12)
            mu0 = min(max(mu0, 1e-6), 1 - 1e-6)
            beta[-1] = np.log(mu0 / (1 - mu0))
        elif family in ("poisson", "gamma", "tweedie"):
            beta[-1] = np.log(max(wy / (n_obs + 1e-12), 1e-6))
        return beta

    def _beta_init(self, yd, wd, family, pdim) -> Tuple[np.ndarray, float]:
        """(β₀, Σw) with the sums reduced ON DEVICE — global + replicated
        under a multi-host mesh, where a host np.asarray of the sharded
        arrays would not be."""
        n_obs, wy = (float(v) for v in _wsums(yd, wd))
        return self._beta_from_sums(wy, n_obs, family, pdim), n_obs

    def _irls_fused(self, Xd, yd, wd, family, lam, alpha, max_iter,
                    beta_eps, tweedie_p, cloud, shard_mode, n_shards,
                    fitplan, y_host=None, w_host=None):
        """Fused whole-fit IRLS (ISSUE 15): one device program, convergence
        on device, host reads final state only. Falls back to the f64 host
        loop if the f32 program diverged (separation-shaped data)."""
        pdim = int(Xd.shape[1])
        if y_host is not None and w_host is not None:
            # HOST init sums: a device jnp.sum over a row-sharded array
            # reduces in psum order, which would break the blocks==mesh
            # bit-identity contract at the very first β
            wts = np.asarray(w_host, np.float64)
            n_obs = float(wts.sum())
            wy = float((wts * np.asarray(y_host, np.float64)).sum())
            beta0 = self._beta_from_sums(wy, n_obs, family, pdim)
        else:
            beta0, n_obs = self._beta_init(yd, wd, family, pdim)
        one_step = (family == "gaussian" and lam >= 0 and alpha * lam == 0)
        fn = _irls_device_fn(cloud, shard_mode, n_shards, family,
                             bool(self._parms.get("non_negative")), one_step)
        with _est.iter_phase():
            # segmented dispatch under QoS (one_step stays a single solve);
            # the β carry round-trips on device between bounded segments
            beta_d = jnp.asarray(beta0, jnp.float32)
            it_d = jnp.int32(0)
            delta_d = jnp.float32(jnp.inf)
            stops = [max_iter] if one_step else _est.segment_stops(max_iter)
            # mid-fit carry snapshots (ISSUE 20): β/it/δ at a segment
            # boundary ARE the whole fit state — a killed fit resumes at
            # the last completed segment, bit-identical (exact f32 carry).
            # λ is in the fingerprint, so every lambda-path solve keeps its
            # own snapshot line.
            ck_fp = _est.segment_fingerprint(
                "glm", rows=int(Xd.shape[0]), p=int(pdim),
                family=str(family), lam=float(lam), alpha=float(alpha),
                max_iter=int(max_iter), beta_eps=float(beta_eps),
                tweedie_p=float(tweedie_p), n_shards=int(n_shards),
                shard_mode=str(shard_mode)) if len(stops) > 1 else None
            rest = _est.segment_carry_restore("glm", ck_fp)
            if rest is not None:
                s0, (beta_d, it_d, delta_d) = rest
                stops = [s for s in stops if s > s0] or [max_iter]
            for stop in stops:
                beta_d, it_d, delta_d = fn(
                    Xd, yd, wd, beta_d, it_d, delta_d,
                    jnp.float32(lam), jnp.float32(alpha),
                    jnp.float32(n_obs), jnp.int32(max_iter),
                    jnp.int32(stop), jnp.float32(beta_eps),
                    jnp.float32(tweedie_p))
                if stop < max_iter:
                    if int(it_d) >= max_iter or float(delta_d) < beta_eps:
                        break
                    _est.segment_carry_save("glm", ck_fp, stop,
                                            (beta_d, it_d, delta_d))
                    _qos.yield_point("est_segment", compensate="est_iter")
            cloudlib.collective_fence(beta_d)
            beta = np.asarray(beta_d, np.float64)
        if not np.isfinite(beta).all():
            # f32 divergence — the robust host loop is the answer, and the
            # plan records that the fused program did not stick
            fitplan.update(path="host_fallback")
            return self._irls(Xd, yd, wd, family, lam, alpha, max_iter,
                              beta_eps, tweedie_p)
        iters = int(it_d)
        fitplan.update(
            path={"mesh": "fused_mesh", "blocks": "fused_blocks"}.get(
                shard_mode, "fused"),
            iterations=iters,
            converged=bool(one_step or float(delta_d) < beta_eps
                           or iters < max_iter))
        return beta

    def _irls(self, Xd, yd, wd, family, lam, alpha, max_iter, beta_eps, tweedie_p):
        pdim = Xd.shape[1]
        beta, n_obs = self._beta_init(yd, wd, family, pdim)
        for it in range(max_iter):
            gram, xy = _gram_step(Xd, yd, wd, jnp.asarray(beta, jnp.float32), family, tweedie_p)
            new_beta = _solve_penalized(
                np.asarray(gram, np.float64), np.asarray(xy, np.float64),
                lam, alpha, n_obs, pdim - 1, beta,
                non_negative=bool(self._parms.get("non_negative")),
            )
            delta = np.max(np.abs(new_beta - beta))
            beta = new_beta
            if delta < beta_eps:
                break
            if family == "gaussian" and lam >= 0 and alpha * lam == 0:
                break  # gaussian ridge/OLS is exact in one step
        return beta

    def _lambda_path(self, Xd, yd, wd, family, alpha, n, nfeat, max_iter,
                     beta_eps, tweedie_p, p, vdata=None, fitplan=None):
        """lambda_search: geometric path from lambda_max down, warm starts
        (hex/glm/GLM.java regularization path). `lambda_best` is chosen by
        VALIDATION deviance when a validation_frame was given (the reference
        selects on held-out deviance; training deviance otherwise, which
        favours the smallest lambda)."""
        fitplan = fitplan if fitplan is not None else {}
        gram0, xy0 = _gram_step(
            Xd, yd, wd, jnp.zeros(Xd.shape[1], jnp.float32), family, tweedie_p
        )
        lam_max = float(np.max(np.abs(np.asarray(xy0)[:-1])) / max(n * max(alpha, 1e-3), 1e-12))
        nlam = int(p.get("nlambdas", -1))
        if nlam <= 0:
            nlam = 30
        ratio = float(p.get("lambda_min_ratio", -1))
        if ratio <= 0:
            ratio = 1e-4 if n > nfeat else 1e-2
        lams = lam_max * np.power(ratio, np.linspace(0, 1, nlam))
        from ..parallel import mesh as cloudlib

        if cloudlib.cloud().size == 1 and not _est.legacy():
            # the whole path runs as ONE device program (f32); the chosen λ
            # is then re-solved on host in f64 for the reported coefficients.
            # H2O3_EST_LEGACY=1 takes the host IRLS loop below instead (the
            # per-λ gram-D2H + host-solve shape, the engine comparator)
            Xe, ye, we = vdata if vdata is not None else (Xd, yd, wd)
            with _est.iter_phase():
                betas, devs = _glm_path_device(
                    Xd, jnp.asarray(yd, jnp.float32), jnp.asarray(wd, jnp.float32),
                    Xe, jnp.asarray(ye, jnp.float32), jnp.asarray(we, jnp.float32),
                    jnp.asarray(lams, jnp.float32), float(alpha),
                    float(np.asarray(wd).sum()),
                    jnp.zeros(Xd.shape[1], jnp.float32), float(beta_eps),
                    float(tweedie_p), family=family, max_iter=int(max_iter),
                    non_negative=bool(self._parms.get("non_negative")),
                )
                betas = np.asarray(betas, np.float64)
                devs = np.asarray(devs, np.float64)
            finite = np.isfinite(devs)
            if finite.any():
                path = [(float(lv), betas[i]) for i, lv in enumerate(lams)]
                best_i = int(np.argmin(np.where(finite, devs, np.inf)))
                lam_best = float(lams[best_i])
                beta = self._irls_warm(Xd, yd, wd, family, lam_best, alpha,
                                       max_iter, beta_eps, tweedie_p,
                                       betas[best_i].copy())
                fitplan.update(path="fused_path", converged=True,
                               iterations=len(lams))
                return beta, lam_best, path
            # every λ diverged in f32 — fall through to the robust host loop

        # host path: multi-host mesh (the fused device path's closure-
        # captured group tensors would embed non-addressable arrays in the
        # HLO; vdata itself is row-sharded and fine), the H2O3_EST_LEGACY
        # comparator, or f32 divergence
        beta = np.zeros(Xd.shape[1], np.float64)
        path = []
        best = (None, np.inf, 0.0)
        for lv in lams:
            beta = self._irls_warm(Xd, yd, wd, family, float(lv), alpha,
                                   max_iter, beta_eps, tweedie_p, beta)
            if vdata is not None:
                dev = self._deviance(vdata[0], vdata[1], vdata[2], family,
                                     beta, tweedie_p)
            else:
                dev = self._deviance(Xd, yd, wd, family, beta, tweedie_p)
            path.append((float(lv), beta.copy()))
            if dev < best[1]:
                best = (beta.copy(), dev, float(lv))
        return best[0], best[2], path

    def _irls_warm(self, Xd, yd, wd, family, lam, alpha, max_iter, beta_eps, tweedie_p, beta0):
        beta = beta0.copy()
        n_obs = float(_wsums(yd, wd)[0])
        for it in range(max_iter):
            gram, xy = _gram_step(Xd, yd, wd, jnp.asarray(beta, jnp.float32), family, tweedie_p)
            new_beta = _solve_penalized(
                np.asarray(gram, np.float64), np.asarray(xy, np.float64),
                lam, alpha, n_obs, Xd.shape[1] - 1, beta,
                non_negative=bool(self._parms.get("non_negative")),
            )
            delta = np.max(np.abs(new_beta - beta))
            beta = new_beta
            if delta < beta_eps:
                break
        return beta

    def _deviance(self, Xd, yd, wd, family, beta, tweedie_p=1.5):
        if distdata.multiprocess():
            # sharded inputs never reach the host; the jitted sum is global
            return float(_deviance_device(
                Xd, yd, wd, jnp.asarray(beta, jnp.float32), family,
                float(tweedie_p)))
        eta = np.asarray(Xd @ jnp.asarray(beta, jnp.float32), np.float64)
        y = np.asarray(yd, np.float64)
        w = np.asarray(wd, np.float64)
        mu = np.asarray(_linkinv(family, jnp.asarray(eta)), np.float64)
        return float(_family_deviance_sum(family, y, mu, w, tweedie_p, xp=np))

    def _fit_multinomial(self, Xd, ycodes, wd, K, alpha, lam, max_iter,
                         n_global=None):
        """Softmax GLM via optax L-BFGS (the reference's multinomial L_BFGS).

        Works unchanged on a multi-host cloud: `Xd`/`wd` arrive row-sharded,
        the local one-hot responses are assembled into a matching global
        array (zero rows in the pad tail carry wd=0), and every reduction
        in `loss` is a jit-global sum."""
        import optax

        pdim = Xd.shape[1]
        n = len(ycodes)
        if distdata.multiprocess():
            Y = np.zeros((n, K), np.float32)
            Y[np.arange(n), ycodes] = 1.0
            from ..parallel import mesh as cloudlib

            Yd = distdata.global_row_array(
                Y, Xd.shape[0] // jax.process_count(), cloudlib.cloud())
        else:
            Y = np.zeros((Xd.shape[0], K), np.float32)
            Y[np.arange(n), ycodes] = 1.0
            Yd = jnp.asarray(Y)
        n_eff = float(n_global if n_global is not None else n)
        lam_v = float(lam[0] if isinstance(lam, (list, tuple)) else (lam or 0.0))

        # data arrays are ARGUMENTS, not closure captures: a jit may not
        # close over process-spanning (multi-host) arrays
        def loss(B, Xd, Yd, wd):
            logits = Xd @ B.T  # (n, K)
            lse = jax.scipy.special.logsumexp(logits, axis=1)
            ll = (jnp.sum(logits * Yd, axis=1) - lse) * wd
            ridge = 0.5 * lam_v * (1 - alpha) * jnp.sum(B[:, :-1] ** 2)
            # sum/n_eff (not mean): the padded global row count must not
            # rescale the data term against the ridge
            return -jnp.sum(ll) / n_eff + ridge

        B = jnp.zeros((K, pdim), jnp.float32)
        try:
            opt = optax.lbfgs()
            state = opt.init(B)

            @jax.jit
            def step(B, state, Xd, Yd, wd):
                def f(b):
                    return loss(b, Xd, Yd, wd)

                v, g = jax.value_and_grad(f)(B)
                updates, state2 = opt.update(g, state, B, value=v, grad=g,
                                             value_fn=f)
                return optax.apply_updates(B, updates), state2, v

            prev = np.inf
            for it in range(max(100, max_iter * 4)):
                B, state, v = step(B, state, Xd, Yd, wd)
                v = float(v)
                if abs(prev - v) < 1e-9:
                    break
                prev = v
        except (AttributeError, TypeError):
            opt = optax.adam(0.1)
            state = opt.init(B)
            vg = jax.jit(jax.value_and_grad(loss))
            for it in range(500):
                v, g = vg(B, Xd, Yd, wd)
                updates, state = opt.update(g, state)
                B = optax.apply_updates(B, updates)
        return np.asarray(B, np.float64)

    def _cv_predict(self, model: GLMModel, frame: Frame) -> np.ndarray:
        out = model._score(frame)
        return out

    # h2o-py convenience
    @staticmethod
    def getGLMRegularizationPath(model):
        m = model.model if isinstance(model, H2OGeneralizedLinearEstimator) else model
        if m.full_path is None:
            return {"lambdas": [m.lambda_best], "coefficients": [m.coef()]}
        return {
            "lambdas": [l for l, _ in m.full_path],
            "coefficients": [dict(zip(m._names(), b)) for _, b in m.full_path],
        }

    def coef(self):
        return self.model.coef()

    def coef_norm(self):
        return self.model.coef_norm()


GLM = H2OGeneralizedLinearEstimator
