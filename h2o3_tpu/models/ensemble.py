"""H2OStackedEnsembleEstimator — super learning.

Reference parity: `h2o-algos/src/main/java/hex/ensemble/StackedEnsemble.java`
/ `StackedEnsembleModel.java` / `Metalearner*.java`: a metalearner (GLM with
non-negative weights by default) trained on the cross-validated holdout
predictions of the base models (which must share fold assignment and
`keep_cross_validation_predictions=True`); `metalearner_algorithm` ∈
{AUTO/glm/gbm/drf/deeplearning}. Client surface
`h2o-py/h2o/estimators/stackedensemble.py`.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..frame.frame import Frame
from .metrics import (
    ModelMetricsBinomial,
    ModelMetricsMultinomial,
    ModelMetricsRegression,
)
from .model_base import H2OEstimator, H2OModel, response_info


class StackedEnsembleModel(H2OModel):
    algo = "stackedensemble"

    def __init__(self, params, base_models, meta_est, problem, nclass, domain, y):
        super().__init__(params)
        self.base_models = base_models
        self.meta = meta_est
        self.problem = problem
        self.nclass = nclass
        self.domain = domain
        self.y = y
        self.x = base_models[0].model.x if base_models else []

    def _level_one(self, frame: Frame) -> Frame:
        import os
        import time

        prof = os.environ.get("H2O3_PROFILE")
        cols = {}
        for i, bm in enumerate(self.base_models):
            t0 = time.time()
            # one base-model prediction per FRAME, shared across ensembles
            # (BestOfFamily ⊆ AllModels would otherwise re-predict every
            # model). Living on the frame object, the cache dies with the
            # frame, cannot collide across frames that reuse a DKV key, and
            # Frame._touch() clears it on any in-place mutation. Computed
            # BEFORE insertion so a failed predict can't poison it.
            preds = frame.__dict__.setdefault("_lvl1_preds", {})
            mid = bm.model.model_id
            if mid not in preds:
                preds[mid] = bm._cv_predict(bm.model, frame)
            p = preds[mid]
            if prof:
                print(f"[h2o3-profile] SE level-one {bm.algo} "
                      f"({bm.model_id}): {time.time()-t0:.2f}s", flush=True)
            if self.problem == "multinomial":
                for k in range(p.shape[1]):
                    cols[f"m{i}_p{k}"] = p[:, k]
            else:
                cols[f"m{i}"] = p if p.ndim == 1 else p[:, 0]
        return Frame.from_dict(cols)

    def predict(self, test_data: Frame) -> Frame:
        lvl1 = self._level_one(test_data)
        return self.meta.predict(lvl1)

    def _score_probs(self, frame: Frame) -> np.ndarray:
        lvl1 = self._level_one(frame)
        return self.meta._cv_predict(self.meta.model, lvl1)

    def _make_metrics(self, frame: Frame):
        out = self._score_probs(frame)
        yv = frame.vec(self.y)
        if self.problem == "binomial":
            return ModelMetricsBinomial.make(np.asarray(yv.data), out)
        if self.problem == "multinomial":
            return ModelMetricsMultinomial.make(np.asarray(yv.data), out)
        return ModelMetricsRegression.make(yv.numeric_np(), out)


class H2OStackedEnsembleEstimator(H2OEstimator):
    algo = "stackedensemble"
    _param_defaults = dict(
        base_models=None,
        metalearner_algorithm="AUTO",
        metalearner_nfolds=0,
        metalearner_params=None,
        metalearner_transform="NONE",
        blending_frame=None,
    )

    def _fit(self, x, y, train: Frame, valid: Optional[Frame]):
        base_models: List = list(self._parms.get("base_models") or [])
        if not base_models:
            raise ValueError("stackedensemble: base_models is required")
        problem, nclass, domain = response_info(train.vec(y))

        blend = self._parms.get("blending_frame")
        cols = {}
        for i, bm in enumerate(base_models):
            if blend is not None:
                p = bm._cv_predict(bm.model, blend)
            else:
                p = bm.model._cv_holdout_pred
                if p is None:
                    raise ValueError(
                        f"base model {bm.model_id} lacks CV holdout predictions; "
                        "train with nfolds>=2 and keep_cross_validation_predictions=True"
                    )
            if problem == "multinomial":
                for k in range(p.shape[1]):
                    cols[f"m{i}_p{k}"] = p[:, k]
            else:
                cols[f"m{i}"] = p if p.ndim == 1 else p[:, 0]
        target_frame = blend if blend is not None else train
        lvl1 = Frame.from_dict(cols)
        yv = target_frame.vec(y)
        lvl1["__y__"] = yv

        algo = self._parms.get("metalearner_algorithm", "AUTO")
        mp = dict(self._parms.get("metalearner_params") or {})
        if algo in ("AUTO", "glm"):
            from .glm import H2OGeneralizedLinearEstimator

            fam = {"binomial": "binomial", "multinomial": "multinomial"}.get(
                problem, "gaussian"
            )
            mp.setdefault("family", fam)
            mp.setdefault("lambda_", 0.0)
            mp.setdefault("non_negative", True)
            meta = H2OGeneralizedLinearEstimator(**mp)
        elif algo == "gbm":
            from .gbm import H2OGradientBoostingEstimator

            meta = H2OGradientBoostingEstimator(**mp)
        elif algo == "drf":
            from .drf import H2ORandomForestEstimator

            meta = H2ORandomForestEstimator(**mp)
        elif algo == "deeplearning":
            from .deeplearning import H2ODeepLearningEstimator

            meta = H2ODeepLearningEstimator(**mp)
        else:
            raise ValueError(f"unknown metalearner_algorithm {algo!r}")
        meta.train(y="__y__", training_frame=lvl1)

        model = StackedEnsembleModel(self, base_models, meta, problem, nclass, domain, y)
        # the SE's training frame IS the level-one frame (out-of-fold base
        # predictions), so the metalearner's training metrics are exactly
        # the SE's cross-validated training metrics — no re-prediction of
        # every base model on the raw frame (which costs seconds per deep
        # forest; upstream StackedEnsemble scores on the level-one frame
        # too: hex/ensemble/StackedEnsemble.java)
        model.training_metrics = meta.model.training_metrics
        if valid is not None:
            model.validation_metrics = model._make_metrics(valid)
        return model

    def _cv_predict(self, model, frame: Frame) -> np.ndarray:
        return model._score_probs(frame)


StackedEnsemble = H2OStackedEnsembleEstimator
