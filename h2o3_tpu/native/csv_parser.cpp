// Native CSV parse — the hot host-side ingest path.
//
// Reference parity: the reference's distributed parse tokenizes byte ranges
// in Java (`water/parser/CsvParser.java` state machine inside the
// `MultiFileParseTask` MRTask); its only native code is the prebuilt XGBoost
// .so. Here the tokenizer itself is native: a single-pass, zero-allocation
// scan with strtod for numerics. The Python layer (frame/parse.py +
// frame/chunked.py) handles setup-guessing, chunk planning and categorical
// interning; this handles the bandwidth.
//
// Exposed via ctypes (native/loader.py):
//   h2o3_csv_parse_numeric_buf(buf, start, end, sep, skip_first, ncol,
//                              out, cap) -> long long
//     Parses the [start, end) byte range of an in-memory buffer — the
//     per-chunk entry the parallel chunked pipeline calls concurrently
//     (ctypes releases the GIL around the call, so chunks really overlap).
//     out == NULL: count non-blank data lines. out != NULL: fill row-major
//     doubles (NaN for NA tokens); returns rows written, -1 if any field
//     is non-numeric (caller falls back to the Python object-column
//     tokenizer), -2 on capacity overflow.
//   h2o3_csv_parse_numeric(path, sep, header, ncol, out, cap)
//     Whole-file wrapper over the same loop (reads the file, then parses
//     [0, size)); kept for the legacy single-chunk path. -2 also covers IO
//     errors here.

#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

// Exactly the NA set of the Python unhinted-column path (Vec.from_numpy):
// "", "NA", "na" — plus "nan", which strtod parses to the same NaN anyway.
// Wider markers ("N/A", "null", "?") must FAIL the parse instead, so the
// caller falls back to the Python tokenizer and both builds agree the
// column is categorical. (The old wider set made dtypes depend on whether
// the .so was built.)
static bool is_na_token(const char* s, size_t n) {
  if (n == 0) return true;
  static const char* kNA[] = {"NA", "na"};
  for (const char* t : kNA) {
    if (strlen(t) == n && strncmp(s, t, n) == 0) return true;
  }
  return false;
}

extern "C" long long h2o3_csv_parse_numeric_buf(
    const char* buf, long long start, long long end, char sep,
    int skip_first, int ncol, double* out, long long cap) {
  const char* p = buf + start;
  const char* bend = buf + end;
  long long row = 0;
  bool skipped_header = (skip_first == 0);

  while (p < bend) {
    const char* line_end = (const char*)memchr(p, '\n', bend - p);
    if (!line_end) line_end = bend;
    const char* le = line_end;
    if (le > p && le[-1] == '\r') --le;
    // blank ≡ the Python `ln.strip()` filter: empty OR whitespace-only
    // lines are dropped, not parsed into all-NA rows
    bool blank = true;
    for (const char* s = p; s < le; ++s) {
      if (*s != ' ' && *s != '\t') { blank = false; break; }
    }
    if (blank) {
      p = line_end + 1;
      continue;
    }
    if (!skipped_header) {
      skipped_header = true;
      p = line_end + 1;
      continue;
    }
    if (!out) {  // count pass: non-blank data lines only, no field parsing
      ++row;
      p = line_end + 1;
      continue;
    }
    if ((row + 1) * (long long)ncol > cap) return -2;
    const char* q = p;
    for (int c = 0; c < ncol; ++c) {
      const char* field_end = q;
      while (field_end < le && *field_end != sep) ++field_end;
      // trim spaces and quotes
      const char* a = q;
      const char* b = field_end;
      while (a < b && (*a == ' ' || *a == '"')) ++a;
      while (b > a && (b[-1] == ' ' || b[-1] == '"')) --b;
      double v;
      if (is_na_token(a, b - a)) {
        v = NAN;
      } else {
        // reject C99 hexfloats ("0x1p3") up front: strtod accepts them but
        // python float() does not, and native success must imply the
        // python path would produce the identical column
        for (const char* s = a; s < b; ++s) {
          if (*s == 'x' || *s == 'X') return -1;
        }
        // strtod in place: fields terminate at sep/newline, both of which
        // stop the conversion (the caller's buffer is contiguous and, for
        // python bytes, NUL-terminated, so reads stay in bounds)
        char* conv_end = nullptr;
        v = strtod(a, &conv_end);
        if (conv_end != b) return -1;  // non-numeric → python fallback
      }
      out[row * ncol + c] = v;
      q = (field_end < le) ? field_end + 1 : le;
    }
    ++row;
    p = line_end + 1;
  }
  return row;
}

extern "C" long long h2o3_csv_parse_numeric(
    const char* path, char sep, int header, int ncol,
    double* out, long long cap) {
  FILE* f = fopen(path, "rb");
  if (!f) return -2;
  fseek(f, 0, SEEK_END);
  long long sz = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::string buf;
  buf.resize(sz);
  if (sz > 0 && fread(&buf[0], 1, sz, f) != (size_t)sz) {
    fclose(f);
    return -2;
  }
  fclose(f);
  return h2o3_csv_parse_numeric_buf(buf.data(), 0, sz, sep, header, ncol,
                                    out, cap);
}
