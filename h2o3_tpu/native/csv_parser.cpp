// Native CSV parse — the hot host-side ingest path.
//
// Reference parity: the reference's distributed parse tokenizes byte ranges
// in Java (`water/parser/CsvParser.java` state machine inside the
// `MultiFileParseTask` MRTask); its only native code is the prebuilt XGBoost
// .so. Here the tokenizer itself is native: a single-pass, zero-allocation
// scan with strtod for numerics. The Python layer (frame/parse.py) handles
// setup-guessing and categorical interning; this handles the bandwidth.
//
// Exposed via ctypes (native/loader.py):
//   h2o3_csv_parse_numeric(path, sep, header, ncol, out, cap) -> long long
//     out == NULL: count data rows; returns -1 if any field is non-numeric
//     (caller falls back to the Python object-column tokenizer), -2 on IO
//     error. out != NULL: fill row-major doubles (NaN for NA tokens),
//     returns rows written.

#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

static bool is_na_token(const char* s, size_t n) {
  if (n == 0) return true;
  static const char* kNA[] = {"NA", "na", "N/A", "nan", "NaN", "null", "NULL", "?"};
  for (const char* t : kNA) {
    if (strlen(t) == n && strncmp(s, t, n) == 0) return true;
  }
  return false;
}

extern "C" long long h2o3_csv_parse_numeric(
    const char* path, char sep, int header, int ncol,
    double* out, long long cap) {
  FILE* f = fopen(path, "rb");
  if (!f) return -2;
  fseek(f, 0, SEEK_END);
  long long sz = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::string buf;
  buf.resize(sz);
  if (sz > 0 && fread(&buf[0], 1, sz, f) != (size_t)sz) {
    fclose(f);
    return -2;
  }
  fclose(f);

  const char* p = buf.data();
  const char* end = p + sz;
  long long row = 0;
  bool skipped_header = (header == 0);

  if (!out) {
    // count pass: non-blank data lines only (no field parsing)
    while (p < end) {
      const char* line_end = (const char*)memchr(p, '\n', end - p);
      if (!line_end) line_end = end;
      const char* le = line_end;
      if (le > p && le[-1] == '\r') --le;
      if (le != p) {
        if (!skipped_header) skipped_header = true;
        else ++row;
      }
      p = line_end + 1;
    }
    return row;
  }

  while (p < end) {
    const char* line_end = (const char*)memchr(p, '\n', end - p);
    if (!line_end) line_end = end;
    const char* q = p;
    const char* le = line_end;
    if (le > p && le[-1] == '\r') --le;
    if (le == p) {  // blank line
      p = line_end + 1;
      continue;
    }
    if (!skipped_header) {
      skipped_header = true;
      p = line_end + 1;
      continue;
    }
    if ((row + 1) * (long long)ncol > cap) return -2;
    for (int c = 0; c < ncol; ++c) {
      const char* field_end = q;
      while (field_end < le && *field_end != sep) ++field_end;
      // trim spaces and quotes
      const char* a = q;
      const char* b = field_end;
      while (a < b && (*a == ' ' || *a == '"')) ++a;
      while (b > a && (b[-1] == ' ' || b[-1] == '"')) --b;
      double v;
      if (is_na_token(a, b - a)) {
        v = NAN;
      } else {
        // strtod in place: fields terminate at sep/newline, both of which
        // stop the conversion (buf is contiguous, so reads stay in bounds)
        char* conv_end = nullptr;
        v = strtod(a, &conv_end);
        if (conv_end != b) return -1;  // non-numeric → python fallback
      }
      out[row * ncol + c] = v;
      q = (field_end < le) ? field_end + 1 : le;
    }
    ++row;
    p = line_end + 1;
  }
  return row;
}
