// Native path-dependent TreeSHAP over heap forests.
//
// Reference parity: `h2o-genmodel/src/main/java/hex/genmodel/algos/tree/
// TreeSHAP.java` (the EXTEND/UNWIND recursion of Lundberg et al.'s
// "Consistent Individualized Feature Attribution for Tree Ensembles"),
// which backs `Model.scoreContributions` / `predict_contributions`.
//
// Trees are the flat perfect-depth heaps of models/tree.py (node i internal
// iff split[i]; children 2i+1/2i+2; NaN and x > thr go right). `cover` is
// the per-node Σ of training row weights recorded by build_tree. The Python
// mirror (and the test oracle) is models/tree_shap.py.
//
// Exposed via ctypes (native/loader.py):
//   h2o3_tree_shap(feat, thr, split, value, cover, ntrees, T,
//                  X, n, F, scale, out)
//     X row-major (n, F) doubles; out (n, F+1) doubles — per-feature phi
//     plus the bias term (cover-weighted forest expectation) in column F.
// OpenMP-parallel over rows.

#include <cmath>
#include <cstdint>
#include <vector>

namespace {

constexpr int kMaxPath = 66;  // supports tree depth up to 64

struct PathEl {
  int d;        // feature of this path element (-1 for the root dummy)
  double z;     // fraction of "cold" (feature-excluded) paths flowing through
  double o;     // fraction of "hot" (feature-included) paths
  double w;     // permutation weight
};

// Remove element i from the path in place (inverse of one EXTEND). The
// recomputed permutation weights stay at their positions — only the
// d/z/o fields shift down (shifting weights too corrupts the path).
inline void unwind(PathEl* m, int& len, int i) {
  const int l = len - 1;
  const double one = m[i].o, zero = m[i].z;
  double nxt = m[l].w;
  for (int j = l - 1; j >= 0; --j) {
    if (one != 0.0) {
      const double tmp = nxt * (l + 1.0) / ((j + 1.0) * one);
      nxt = m[j].w - tmp * zero * (l - j) / (l + 1.0);
      m[j].w = tmp;
    } else {
      m[j].w = m[j].w * (l + 1.0) / (zero * (l - j));
    }
  }
  for (int j = i; j < l; ++j) {
    m[j].d = m[j + 1].d;
    m[j].z = m[j + 1].z;
    m[j].o = m[j + 1].o;
  }
  len = l;
}

// Σ path weights with element i unwound, without mutating the path.
inline double unwound_sum(const PathEl* m, int len, int i) {
  const int l = len - 1;
  const double one = m[i].o, zero = m[i].z;
  double total = 0.0, nxt = m[l].w;
  for (int j = l - 1; j >= 0; --j) {
    if (one != 0.0) {
      const double tmp = nxt * (l + 1.0) / ((j + 1.0) * one);
      total += tmp;
      nxt = m[j].w - tmp * zero * (l - j) / (l + 1.0);
    } else {
      total += m[j].w * (l + 1.0) / (zero * (l - j));
    }
  }
  return total;
}

void recurse(const int32_t* feat, const float* thr, const uint8_t* split,
             const float* value, const float* cover, const double* x,
             double* phi, double scale, int node, const PathEl* parent,
             int plen, double pz, double po, int pi) {
  // each level owns a copy: a repeated feature unwinds a middle element,
  // and the parent's path must stay intact for the cold branch
  PathEl m[kMaxPath];
  for (int i = 0; i < plen; ++i) m[i] = parent[i];
  int len = plen;
  m[len] = {pi, pz, po, len == 0 ? 1.0 : 0.0};
  for (int i = len - 1; i >= 0; --i) {
    m[i + 1].w += po * m[i].w * (i + 1.0) / (len + 1.0);
    m[i].w = pz * m[i].w * (len - i) / (len + 1.0);
  }
  ++len;

  if (!split[node]) {
    const double v = (double)value[node] * scale;
    for (int i = 1; i < len; ++i)
      phi[m[i].d] += unwound_sum(m, len, i) * (m[i].o - m[i].z) * v;
    return;
  }

  const int f = feat[node];
  const double xv = x[f];
  const bool right = std::isnan(xv) || xv > (double)thr[node];
  const int hot = 2 * node + 1 + (right ? 1 : 0);
  const int cold = 2 * node + 1 + (right ? 0 : 1);
  const double cn = cover[node];
  const double denom = cn > 0.0 ? cn : 1.0;
  double iz = 1.0, io = 1.0;
  for (int i = 1; i < len; ++i) {
    if (m[i].d == f) {
      iz = m[i].z;
      io = m[i].o;
      unwind(m, len, i);
      break;
    }
  }
  recurse(feat, thr, split, value, cover, x, phi, scale, hot, m, len,
          iz * cover[hot] / denom, io, f);
  recurse(feat, thr, split, value, cover, x, phi, scale, cold, m, len,
          iz * cover[cold] / denom, 0.0, f);
}

}  // namespace

extern "C" void h2o3_tree_shap(
    const int32_t* feat, const float* thr, const uint8_t* split,
    const float* value, const float* cover, int ntrees, int T,
    const double* X, long long n, int F, double scale, double* out) {
  // per-tree expectation (bias term), computed once by an upward pass
  std::vector<double> ev((size_t)T);
  double bias = 0.0;
  for (int t = 0; t < ntrees; ++t) {
    const long long off = (long long)t * T;
    for (int i = T - 1; i >= 0; --i) {
      if (!split[off + i] || 2 * i + 2 >= T) {
        ev[i] = (double)value[off + i];
      } else {
        const double cn = (double)cover[off + i];
        ev[i] = cn > 0.0
                    ? ((double)cover[off + 2 * i + 1] * ev[2 * i + 1] +
                       (double)cover[off + 2 * i + 2] * ev[2 * i + 2]) / cn
                    : (double)value[off + i];
      }
    }
    bias += ev[0] * scale;
  }

#pragma omp parallel for schedule(static)
  for (long long r = 0; r < n; ++r) {
    const double* xi = X + r * (long long)F;
    double* phi = out + r * (long long)(F + 1);
    for (int j = 0; j <= F; ++j) phi[j] = 0.0;
    phi[F] = bias;
    for (int t = 0; t < ntrees; ++t) {
      const long long off = (long long)t * T;
      recurse(feat + off, thr + off, split + off, value + off, cover + off,
              xi, phi, scale, 0, nullptr, 0, 1.0, 1.0, -1);
    }
  }
}
