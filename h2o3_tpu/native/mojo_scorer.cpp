// Native MOJO scorer — standalone forest traversal.
//
// Reference parity: `h2o-genmodel/src/main/java/hex/genmodel/algos/tree/`
// (`SharedTreeMojoModel.scoreTree` — the dependency-free tree walk behind
// `EasyPredictModelWrapper`). The artifact layout here is the flat heap
// forest of models/tree.py: per tree, arrays feat/thr/is_split/value of
// length 2^(D+1)-1; traversal sends NaN and x > thr right, matching
// predict_raw (NA-bin-is-last training semantics).
//
// Exposed via ctypes (native/loader.py):
//   h2o3_score_forest(feat, thr, split, value, ntrees, T, max_depth,
//                     X, n, F, out)
//     X row-major (n, F) doubles; out (n,) receives the summed leaf values.
// OpenMP-parallel over rows.

#include <cmath>
#include <cstdint>

extern "C" void h2o3_score_forest(
    const int32_t* feat, const float* thr, const uint8_t* split,
    const float* value, int ntrees, int T, int max_depth,
    const double* X, long long n, int F, double* out) {
#pragma omp parallel for schedule(static)
  for (long long i = 0; i < n; ++i) {
    const double* xi = X + i * (long long)F;
    double acc = 0.0;
    for (int t = 0; t < ntrees; ++t) {
      const long long off = (long long)t * T;
      const int32_t* tf = feat + off;
      const float* tt = thr + off;
      const uint8_t* ts = split + off;
      int node = 0;
      for (int d = 0; d < max_depth; ++d) {
        if (!ts[node]) break;
        double x = xi[tf[node]];
        bool right = std::isnan(x) || x > (double)tt[node];
        node = 2 * node + 1 + (right ? 1 : 0);
      }
      acc += (double)value[off + node];
    }
    out[i] = acc;
  }
}
