"""ctypes bridge to the native C++ data-plane helpers.

Reference parity: the reference's only native code enters via prebuilt
XGBoost `.so`s (`h2o-ext-xgboost`, see SURVEY.md §2.3); its parser is Java
(`water/parser/CsvParser.java`). Here the hot host-side paths (CSV
tokenization) get a real C++ implementation (`csv_parser.cpp`) compiled to
`libh2o3native.so` and loaded lazily; every caller must tolerate `None`
returns and fall back to the numpy path so the framework works without the
toolchain.
"""

from __future__ import annotations

import ctypes
import os
from typing import List, Optional

import numpy as np

_LIB = None
_TRIED = False


def _lib():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    here = os.path.dirname(__file__)
    so = os.path.join(here, "libh2o3native.so")
    srcs = [os.path.join(here, f) for f in os.listdir(here) if f.endswith(".cpp")]
    stale = os.path.exists(so) and any(
        os.path.getmtime(s) > os.path.getmtime(so) for s in srcs
    )
    if not os.path.exists(so) or stale:
        # (re)build on first use — the .so is not shipped (platform-specific)
        # and a stale lib (older than its sources) would miss newer symbols
        import subprocess

        try:
            subprocess.run(
                ["make", "-B", "-C", here] if stale else ["make", "-C", here],
                capture_output=True, timeout=120, check=True,
            )
        except (OSError, subprocess.SubprocessError):
            if not os.path.exists(so):
                return None
    if os.path.exists(so):
        try:
            _LIB = ctypes.CDLL(so)
        except OSError:
            _LIB = None
    return _LIB


def available() -> bool:
    return _lib() is not None


def score_forest(feat: np.ndarray, thr: np.ndarray, split: np.ndarray,
                 value: np.ndarray, max_depth: int, X: np.ndarray
                 ) -> Optional[np.ndarray]:
    """Native heap-forest traversal (mojo_scorer.cpp). Arrays are the
    (ntrees, T) stacked fields of one class's forest; X row-major (n, F)
    float64. Returns summed leaf values (n,) or None without the lib."""
    lib = _lib()
    if lib is None:
        return None
    try:
        fn = lib.h2o3_score_forest
    except AttributeError:
        return None
    feat = np.ascontiguousarray(feat, np.int32)
    thr = np.ascontiguousarray(thr, np.float32)
    split = np.ascontiguousarray(split).astype(np.uint8)
    value = np.ascontiguousarray(value, np.float32)
    X = np.ascontiguousarray(X, np.float64)
    ntrees, T = feat.shape
    n, F = X.shape
    out = np.empty(n, np.float64)
    fn.restype = None
    fn.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_float),
        ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_double), ctypes.c_longlong, ctypes.c_int,
        ctypes.POINTER(ctypes.c_double),
    ]
    fn(feat.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
       thr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
       split.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
       value.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
       ntrees, T, max_depth,
       X.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), n, X.shape[1],
       out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    return out


def tree_shap(feat: np.ndarray, thr: np.ndarray, split: np.ndarray,
              value: np.ndarray, cover: np.ndarray, X: np.ndarray,
              scale: float = 1.0) -> Optional[np.ndarray]:
    """Native path-dependent TreeSHAP (tree_shap.cpp). Arrays are the
    (ntrees, T) stacked fields + covers of one class's forest; X row-major
    (n, F) float64. Returns (n, F+1) contributions (+BiasTerm last) or None
    without the lib."""
    lib = _lib()
    if lib is None:
        return None
    try:
        fn = lib.h2o3_tree_shap
    except AttributeError:
        return None
    feat = np.ascontiguousarray(feat, np.int32)
    thr = np.ascontiguousarray(thr, np.float32)
    split = np.ascontiguousarray(split).astype(np.uint8)
    value = np.ascontiguousarray(value, np.float32)
    cover = np.ascontiguousarray(cover, np.float32)
    X = np.ascontiguousarray(X, np.float64)
    ntrees, T = feat.shape
    n, F = X.shape
    out = np.empty((n, F + 1), np.float64)
    fn.restype = None
    fn.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float), ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_double), ctypes.c_longlong, ctypes.c_int,
        ctypes.c_double, ctypes.POINTER(ctypes.c_double),
    ]
    fn(feat.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
       thr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
       split.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
       value.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
       cover.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
       ntrees, T,
       X.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), n, F,
       float(scale),
       out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    return out


def tokenize_chunk_numeric(data: bytes, start: int, end: int, sep: str,
                           ncol: int, skip_first_line: bool
                           ) -> Optional[np.ndarray]:
    """Native numeric tokenize of one [start, end) byte chunk of an
    in-memory CSV payload — the per-chunk worker of the parallel pipeline
    (frame/chunked.py). ctypes releases the GIL for the call, so chunk
    workers overlap on real cores. Returns an (nrows, ncol) float64 matrix,
    or None when the lib is absent or any field is non-numeric (the caller
    falls back to the Python object-column tokenizer for EVERY chunk —
    mixing float and token chunks would corrupt the categorical intern)."""
    lib = _lib()
    if lib is None:
        return None
    try:
        fn = lib.h2o3_csv_parse_numeric_buf
    except AttributeError:
        return None
    try:
        fn.restype = ctypes.c_longlong
        fn.argtypes = [
            ctypes.c_char_p, ctypes.c_longlong, ctypes.c_longlong,
            ctypes.c_char, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_double), ctypes.c_longlong,
        ]
        sep_b = sep.encode()
        if len(sep_b) != 1:
            return None
        nrows = fn(data, start, end, sep_b, 1 if skip_first_line else 0,
                   ncol, None, 0)
        if nrows < 0:
            return None
        buf = np.empty((nrows, ncol), dtype=np.float64)
        got = fn(data, start, end, sep_b, 1 if skip_first_line else 0, ncol,
                 buf.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                 nrows * ncol)
        if got != nrows:
            return None
        return buf
    except (OSError, ValueError):
        return None


def tokenize_csv(path: str, sep: str, header: bool, ncol: int) -> Optional[List[np.ndarray]]:
    """Fast numeric-first CSV tokenize. Returns per-column object arrays, or
    None when the native lib is absent (callers fall back to numpy)."""
    lib = _lib()
    if lib is None:
        return None
    try:
        lib.h2o3_csv_parse_numeric.restype = ctypes.c_longlong
        lib.h2o3_csv_parse_numeric.argtypes = [
            ctypes.c_char_p, ctypes.c_char, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_double), ctypes.c_longlong,
        ]
        # first pass: count rows
        nrows = lib.h2o3_csv_parse_numeric(
            path.encode(), sep.encode()[0], 1 if header else 0, ncol, None, 0
        )
        if nrows < 0:
            return None  # non-numeric content: let python path handle enums
        buf = np.empty((nrows, ncol), dtype=np.float64)
        got = lib.h2o3_csv_parse_numeric(
            path.encode(), sep.encode()[0], 1 if header else 0, ncol,
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), nrows * ncol,
        )
        if got != nrows:
            return None
        return [buf[:, i] for i in range(ncol)]
    except (AttributeError, OSError):
        return None
