"""Ingest observability — per-parse phase timings + throughput counters.

The parse pipeline (frame/parse.py, frame/distributed_parse.py) records one
entry per completed parse: rows, bytes, wall seconds and the per-phase
split (setup / read / tokenize / coerce / intern / place — the stages of
`ParseDataset`'s progress reporting, `water/parser/ParseDataset.java`
Job progress units). Readers:

- `GET /3/Ingest/metrics` and the `ingest` section of `/3/Profiler`
  (via runtime/profiler.ingest_stats) serve `snapshot()`;
- `runtime/phases.py` receives the same marks under ``ingest_<stage>``
  keys, so bench.py's phase decomposition covers ingest next to
  h2d/compile/compute.

Phase bucketing: "coerce" books columns that resolve numeric/time (the
vectorized astype-with-NA-masking pass), "intern" books enum/string
columns (the categorical intern, and on the distributed path the phase-2
domain-union collectives too).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional

_LOCK = threading.Lock()
_TOTALS = dict(parses=0, rows=0, bytes=0, secs=0.0)
_LAST: Dict = {}

PHASE_ORDER = ("setup", "read", "tokenize", "coerce", "intern", "place")


_REGISTRY = None


def _registry():
    """Central-registry counters backing the /3/Ingest/metrics totals (the
    scrape surface at GET /3/Metrics). Registered lazily (memoized — this
    runs per parse) and bound to the REST fields they back so the
    metrics-consistency test can hold the two surfaces together."""
    global _REGISTRY
    if _REGISTRY is not None:
        return _REGISTRY
    from ..runtime import metrics_registry as reg

    c = {
        "parses": reg.counter("h2o3_ingest_parses",
                              "completed CSV parses"),
        "rows": reg.counter("h2o3_ingest_rows", "rows ingested"),
        "bytes": reg.counter("h2o3_ingest_bytes", "bytes ingested"),
        "secs": reg.counter("h2o3_ingest_seconds",
                            "wall seconds spent parsing"),
    }
    for field, metric in (("totals.parses", "h2o3_ingest_parses"),
                          ("totals.rows", "h2o3_ingest_rows"),
                          ("totals.bytes", "h2o3_ingest_bytes"),
                          ("totals.secs", "h2o3_ingest_seconds")):
        reg.bind_rest_field("ingest", field, metric)
    _REGISTRY = c
    return c


@contextmanager
def stage(marks: Dict[str, float], name: str):
    """Accumulate wall-clock of one parse stage into `marks[name]`."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        marks[name] = marks.get(name, 0.0) + (time.perf_counter() - t0)


def record(path: str, rows: int, nbytes: int, secs: float,
           phases: Dict[str, float], n_chunks: int = 1, n_threads: int = 1,
           native: bool = False, distributed: bool = False,
           legacy: bool = False) -> None:
    """Book one finished parse into the cumulative totals + `last`, and
    forward the stage marks to runtime/phases as ``ingest_*``."""
    from ..runtime import phases as _phz

    for k, v in phases.items():
        _phz.add(f"ingest_{k}", v,
                 nbytes=nbytes if k == "tokenize" else 0)
    secs = max(secs, 1e-9)
    entry = dict(
        path=path, rows=int(rows), bytes=int(nbytes),
        secs=round(secs, 4),
        rows_per_s=round(rows / secs, 1),
        bytes_per_s=round(nbytes / secs, 1),
        n_chunks=int(n_chunks), n_threads=int(n_threads),
        native=bool(native), distributed=bool(distributed),
        phases={k: round(phases.get(k, 0.0), 4)
                for k in PHASE_ORDER if k in phases},
    )
    if legacy:
        entry["legacy"] = True
    with _LOCK:
        _TOTALS["parses"] += 1
        _TOTALS["rows"] += int(rows)
        _TOTALS["bytes"] += int(nbytes)
        _TOTALS["secs"] += secs
        _LAST.clear()
        _LAST.update(entry)
    # observability spine: monotone registry counters (GET /3/Metrics) +
    # a retroactive child span of whatever request/job ran this parse
    reg = _registry()
    reg["parses"].inc(1)
    reg["rows"].inc(int(rows))
    reg["bytes"].inc(int(nbytes))
    reg["secs"].inc(secs)
    from ..runtime import tracing as _tracing

    _tracing.record_span(f"ingest:{path}", secs, kind="ingest",
                         rows=int(rows), bytes=int(nbytes),
                         n_chunks=int(n_chunks), native=bool(native))


def snapshot() -> Dict:
    """Cumulative + last-parse counters (the /3/Ingest/metrics body)."""
    with _LOCK:
        totals = dict(_TOTALS)
        last: Optional[Dict] = dict(_LAST) if _LAST else None
    secs = max(totals["secs"], 1e-9)
    out = dict(
        totals=dict(
            parses=totals["parses"], rows=totals["rows"],
            bytes=totals["bytes"], secs=round(totals["secs"], 4),
            rows_per_s=round(totals["rows"] / secs, 1),
            bytes_per_s=round(totals["bytes"] / secs, 1),
        ),
        last=last,
    )
    return out


def reset() -> None:
    with _LOCK:
        _TOTALS.update(parses=0, rows=0, bytes=0, secs=0.0)
        _LAST.clear()
