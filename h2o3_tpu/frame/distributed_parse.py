"""Distributed CSV ingest — per-process byte-range parse + two-phase global
categorical interning.

Reference parity: `h2o-core/src/main/java/water/parser/ParseDataset.java`
(`MultiFileParseTask` — each node parses the byte ranges it homes),
`water/parser/Categorical.java` (per-node interning then a global merge and
renumber pass), `water/parser/ParseSetup.java` (the setup guess runs on a
sample and is therefore identical on every node).

TPU-native shape: phase 1 is embarrassingly parallel — process r parses
bytes [r·S/n, (r+1)·S/n) of the file, with MapReduce split semantics (a
process starts at the first line AFTER its range start unless it owns byte
0, and finishes the line that straddles its range end). Phase 2 unions the
per-process categorical domains and column-kind votes over the JAX
coordination service (`multihost_utils.process_allgather` — the
Categorical merge as a collective instead of DKV traffic), then every
process renumbers its local codes against the agreed global domain.

The result is BIT-IDENTICAL to the single-process `parse_csv`: a column is
numeric only if it parses numeric on EVERY process (matching the whole-file
try in `Vec.from_numpy`), domains are the sorted global uniques (matching
`np.unique` over the whole column), and codes/NaNs follow the same NA token
rules. With one process the byte range is the whole file and no collective
runs.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .frame import Frame
from .parse import _NA_TOKENS, parse_setup
from .vec import Vec, bulk_try_numeric

# NA tokens of Vec.from_numpy's intern path — kept separate from the parser's
# wider _NA_TOKENS so distributed enum codes stay bit-identical to the
# single-process Vec.from_numpy result
_ENUM_NA = ("", "NA", "na", None)
_NUM_NA = ("", "NA", "na", "nan", None)


class DistInfo:
    """Placement facts of a process-local shard of a distributed Frame."""

    __slots__ = ("process_index", "process_count", "local_nrow",
                 "global_nrow", "row_offset")

    def __init__(self, process_index, process_count, local_nrow,
                 global_nrow, row_offset):
        self.process_index = process_index
        self.process_count = process_count
        self.local_nrow = local_nrow
        self.global_nrow = global_nrow
        self.row_offset = row_offset


# -- coordination primitives (no-ops in a 1-process cloud) -------------------
def _process_count() -> int:
    import jax

    return jax.process_count()


def _allgather_int(value: int) -> List[int]:
    """All processes learn everyone's scalar (e.g. local row counts).
    int32 transport — callers' values (row counts, payload lengths) are
    bounded well under 2^31; cross-process SUMS happen on host in Python
    ints afterwards, so totals don't wrap."""
    if _process_count() == 1:
        return [int(value)]
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    out = multihost_utils.process_allgather(jnp.asarray([value], jnp.int32))
    return [int(v) for v in np.asarray(out).reshape(-1)]


def _allgather_f64_vec(vec: np.ndarray) -> np.ndarray:
    """(nproc, len(vec)) gather of a small f64 fact vector — raw-byte
    transport (see distdata.allgather_host) so boundary-exact comparisons
    (e.g. the 2^24 downcast threshold) survive."""
    from ..parallel.distdata import allgather_host

    return allgather_host(np.asarray(vec, np.float64))


def _allgather_bytes(payload: bytes) -> List[bytes]:
    from ..parallel.distdata import allgather_bytes

    return allgather_bytes(payload)


def _union_domains(local: List[str]) -> List[str]:
    """Phase-2 Categorical merge: sorted union of every process's local
    uniques ≡ np.unique over the whole column."""
    payload = "\x00".join(local).encode("utf-8")
    parts = _allgather_bytes(payload)
    seen = set()
    for blob in parts:
        s = blob.decode("utf-8")
        if s:
            seen.update(s.split("\x00"))
    seen.discard("")
    return sorted(seen)


# -- phase 1: byte-range tokenize -------------------------------------------
def byte_range(size: int, rank: int, nranks: int) -> Tuple[int, int]:
    per = size // nranks
    start = rank * per
    end = size if rank == nranks - 1 else (rank + 1) * per
    return start, end


def read_range_lines(path: str, start: int, end: int) -> List[str]:
    """Lines of the byte range with MultiFileParseTask split semantics:
    skip the partial line at `start` (the previous range finishes it), and
    finish the line straddling `end`."""
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        if start > 0:
            f.seek(start - 1)
            prev = f.read(1)
            if prev != b"\n":
                # mid-line: the owner of the previous range emits this line
                while True:
                    chunk = f.read(1 << 16)
                    if not chunk:
                        return []
                    nl = chunk.find(b"\n")
                    if nl >= 0:
                        f.seek(f.tell() - len(chunk) + nl + 1)
                        break
        pos = f.tell()
        if pos >= end:
            return []
        data = f.read(end - pos)
        # extend through the straddling line
        if not data.endswith(b"\n") and end < size:
            while True:
                chunk = f.read(1 << 16)
                if not chunk:
                    break
                nl = chunk.find(b"\n")
                if nl >= 0:
                    data += chunk[: nl + 1]
                    break
                data += chunk
    text = data.decode("utf-8", errors="replace")
    return [ln for ln in text.splitlines() if ln.strip()]


# -- phase 2+3: global type vote, domain union, renumber ---------------------
def _try_numeric(col: np.ndarray):
    try:
        # tokenizer columns are str by construction → skip the type scan
        return bulk_try_numeric(col, _NUM_NA, assume_str=True)
    except (TypeError, ValueError):
        return None


def _vec_with_domain(col: np.ndarray, domain: List[str]) -> Vec:
    """Enum Vec against an agreed GLOBAL domain (sorted), same NA rule as
    Vec.from_numpy's intern path."""
    mask = np.asarray([v in _ENUM_NA for v in col])
    dom = np.asarray(domain, dtype=object)
    codes = np.searchsorted(dom, np.asarray(col)[~mask])
    full = np.full(len(col), -1, dtype=np.int32)
    full[~mask] = codes.astype(np.int32)
    return Vec(full, "enum", domain=[str(d) for d in domain])


def parse_csv_distributed(
    path: str,
    sep: Optional[str] = None,
    header: Optional[bool] = None,
    col_names: Optional[Sequence[str]] = None,
    col_types: Optional[Dict[str, str]] = None,
) -> Frame:
    """Parse this process's byte range of `path`; phase-2 collectives make
    types/domains globally consistent. Returns the LOCAL-row Frame with a
    `.dist` DistInfo (global row facts). One process ⇒ whole file, no
    collectives — identical to `parse_csv`."""
    import jax

    from . import chunked as _chunked
    from . import ingest_stats as _stats

    t_start = time.perf_counter()
    marks: Dict[str, float] = {}
    rank, nranks = jax.process_index(), jax.process_count()
    with _stats.stage(marks, "setup"):
        setup = parse_setup(path, sep=sep)  # deterministic ⇒ same on every rank
        if header is None:
            header = setup["header"]
        names = list(col_names) if col_names else setup["names"]
        sep = setup["sep"]

    size = os.path.getsize(path)
    start, end = byte_range(size, rank, nranks)
    with _stats.stage(marks, "read"):
        lines = read_range_lines(path, start, end)
    if header and rank == 0 and lines:
        lines = lines[1:]
    # phase-1 tokenize of this process's range: parallel row blocks through
    # the same vectorized tokenizer as parse_csv (bit-identical to the old
    # _split_lines pass, pinned by tests/test_parse_parallel.py)
    with _stats.stage(marks, "tokenize"):
        cols, tok_info = _chunked.tokenize_lines(lines, sep, len(names))

    col_types = col_types or {}
    vecs: Dict[str, Vec] = {}
    for i, name in enumerate(names):
        t_col = time.perf_counter()
        v = _coerce_column_global(cols[i], col_types.get(name))
        # numeric/time columns book "coerce"; enum/string book "intern"
        # (incl. the phase-2 domain-union collectives) — same buckets as
        # parse_csv, surfaced at /3/Profiler and /3/Ingest/metrics
        bucket = "intern" if v.type in ("enum", "string") else "coerce"
        marks[bucket] = marks.get(bucket, 0.0) + (time.perf_counter() - t_col)
        vecs[name] = v

    with _stats.stage(marks, "place"):
        fr = Frame(vecs, key=os.path.basename(path))
        local_n = fr.nrow
        counts = _allgather_int(local_n)
        fr.dist = DistInfo(rank, nranks, local_n, sum(counts),
                           sum(counts[:rank]))
    _stats.record(path, local_n, end - start,
                  time.perf_counter() - t_start, marks, distributed=True,
                  **tok_info)
    return fr


def _coerce_column_global(col: np.ndarray, hint: Optional[str]) -> Vec:
    """Coerce one tokenized column with GLOBALLY consistent type/domain
    decisions (the collectives replacing the reference's Categorical/DKV
    traffic)."""
    if hint in ("real", "int", "numeric", "float"):
        vals = bulk_try_numeric(col, _NA_TOKENS, strip_tokens=True,
                                assume_str=True)
        fin = vals[np.isfinite(vals)]
        mx = float(np.abs(fin).max()) if fin.size else 0.0
        big = float(_allgather_f64_vec(np.asarray([mx]))[:, 0].max())
        # global _maybe_f32: downcast only if the WHOLE column fits
        return Vec(vals if big > (1 << 24)
                   else vals.astype(np.float32), "real")
    if hint == "string":
        return Vec(None, "string", strings=col)
    # numeric unless ANY process fails to parse numeric (the whole-file
    # try of Vec.from_numpy). One fact vector per column:
    # [parses_numeric, has_finite, all_int_or_abstain, max_abs] — an
    # all-NA shard abstains from the int vote, and the f32 downcast is
    # decided on the GLOBAL max magnitude (both match Vec.from_numpy
    # over the whole column).
    as_num = None if hint in ("enum", "factor", "categorical") \
        else _try_numeric(col)
    if as_num is not None:
        fin = as_num[np.isfinite(as_num)]
        facts = [1.0, float(fin.size > 0),
                 1.0 if (fin.size == 0
                         or bool(np.all(fin == np.round(fin)))) else 0.0,
                 float(np.abs(fin).max()) if fin.size else 0.0]
    else:
        facts = [0.0, 0.0, 0.0, 0.0]
    gf = _allgather_f64_vec(np.asarray(facts))
    if as_num is not None and bool(np.all(gf[:, 0] == 1.0)):
        is_int = bool(np.any(gf[:, 1] > 0)) and bool(np.all(gf[:, 2] == 1.0))
        big = float(gf[:, 3].max())
        return Vec(as_num if big > (1 << 24)
                   else as_num.astype(np.float32),
                   "int" if is_int else "real")
    local_dom = sorted(
        {str(v) for v in col if v not in _ENUM_NA})
    return _vec_with_domain(col, _union_domains(local_dom))
