"""Frame — distributed columnar table.

Reference parity: `h2o-core/src/main/java/water/fvec/Frame.java`. A Frame is
an ordered set of named `Vec`s of equal length. Unlike the reference (chunks
homed per-node in the DKV, `water/DKV.java`), columns here are dense JAX
arrays; row-sharding over the ``hosts`` mesh axis happens at compute time via
`NamedSharding` (see `h2o3_tpu/parallel/mesh.py`), which is where H2O's
"home node" concept goes on a TPU pod.

Munging surface mirrors the parts of `h2o-py/h2o/frame.py` (H2OFrame) that
the reference's own tests exercise: indexing, split_frame, cbind/rbind,
describe/summary, type coercion. The lazy-ExprNode/Rapids indirection
(`h2o-core/.../water/rapids/`) is collapsed: clients are in-process, so ops
execute eagerly — see `h2o3_tpu/frame/rapids.py` for the expression layer.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from .vec import Vec

_key_counter = itertools.count()


class Frame:
    def __init__(self, vecs: Dict[str, Vec], key: Optional[str] = None):
        lens = {len(v) for v in vecs.values()}
        if len(lens) > 1:
            raise ValueError(f"ragged frame: column lengths {lens}")
        self._vecs: Dict[str, Vec] = dict(vecs)
        self.key = key or f"frame_{next(_key_counter)}"

    # -- construction -------------------------------------------------------
    @staticmethod
    def from_numpy(
        arr: np.ndarray,
        names: Optional[Sequence[str]] = None,
        column_types: Optional[Dict[str, str]] = None,
    ) -> "Frame":
        arr = np.atleast_2d(np.asarray(arr))
        names = list(names) if names else [f"C{i+1}" for i in range(arr.shape[1])]
        column_types = column_types or {}
        return Frame(
            {n: Vec.from_numpy(arr[:, i], column_types.get(n)) for i, n in enumerate(names)}
        )

    @staticmethod
    def from_dict(d: Dict[str, Sequence], column_types: Optional[Dict[str, str]] = None) -> "Frame":
        column_types = column_types or {}
        return Frame(
            {n: Vec.from_numpy(np.asarray(c), column_types.get(n)) for n, c in d.items()}
        )

    # -- shape / metadata ---------------------------------------------------
    @property
    def names(self) -> List[str]:
        return list(self._vecs)

    @property
    def columns(self) -> List[str]:
        return self.names

    @property
    def ncol(self) -> int:
        return len(self._vecs)

    @property
    def nrow(self) -> int:
        return len(next(iter(self._vecs.values()))) if self._vecs else 0

    @property
    def shape(self):
        return (self.nrow, self.ncol)

    @property
    def types(self) -> Dict[str, str]:
        return {n: v.type for n, v in self._vecs.items()}

    def vec(self, name: str) -> Vec:
        return self._vecs[name]

    def vecs(self) -> List[Vec]:
        return list(self._vecs.values())

    # -- indexing -----------------------------------------------------------
    def __getitem__(self, item) -> "Frame":
        # f["col"] / f[["a","b"]] -> column subset
        if isinstance(item, str):
            return Frame({item: self._vecs[item]})
        if isinstance(item, (list, tuple)) and item and all(isinstance(i, str) for i in item):
            return Frame({n: self._vecs[n] for n in item})
        if isinstance(item, (list, tuple)) and item and all(isinstance(i, (int, np.integer)) for i in item):
            names = self.names
            return Frame({names[i]: self._vecs[names[i]] for i in item})
        if isinstance(item, int):
            n = self.names[item]
            return Frame({n: self._vecs[n]})
        # boolean mask / row index array / slice
        if isinstance(item, slice):
            idx = np.arange(self.nrow)[item]
            return self.take(idx)
        if isinstance(item, (np.ndarray, list)):
            idx = np.asarray(item)
            if idx.dtype == bool:
                idx = np.nonzero(idx)[0]
            return self.take(idx)
        if isinstance(item, tuple) and len(item) == 2:
            rows, cols = item
            sub = self[cols] if not isinstance(cols, slice) else self
            return sub[rows] if not isinstance(rows, slice) or rows != slice(None) else sub
        raise TypeError(f"bad index {item!r}")

    def __setitem__(self, name: str, value) -> None:
        if isinstance(value, Frame):
            value = value.vecs()[0]
        if not isinstance(value, Vec):
            value = Vec.from_numpy(np.asarray(value))
        if self._vecs and len(value) != self.nrow:
            raise ValueError("length mismatch")
        self._vecs[name] = value

    def take(self, idx: np.ndarray) -> "Frame":
        return Frame({n: v.take(idx) for n, v in self._vecs.items()})

    def drop(self, names: Union[str, Sequence[str]]) -> "Frame":
        if isinstance(names, str):
            names = [names]
        return Frame({n: v for n, v in self._vecs.items() if n not in set(names)})

    # -- combination --------------------------------------------------------
    def cbind(self, other: "Frame") -> "Frame":
        out = dict(self._vecs)
        for n, v in other._vecs.items():
            nn = n
            while nn in out:
                nn = nn + "0"  # h2o dedup convention
            out[nn] = v
        return Frame(out)

    def rbind(self, other: "Frame") -> "Frame":
        if self.names != other.names:
            raise ValueError("rbind: column names differ")
        out = {}
        for n in self.names:
            a, b = self._vecs[n], other._vecs[n]
            if a.type == "enum" or b.type == "enum":
                da = a.domain or []
                db = b.domain or []
                dom = list(dict.fromkeys(da + db))
                remap_b = np.asarray([dom.index(x) for x in db], dtype=np.int32) if db else np.zeros(0, np.int32)
                ca = np.asarray(a.data)
                cb = np.asarray(b.data)
                cb = np.where(cb >= 0, remap_b[np.maximum(cb, 0)], -1)
                out[n] = Vec(np.concatenate([ca, cb]), "enum", domain=dom)
            else:
                out[n] = Vec(
                    np.concatenate([a.to_numpy(), b.to_numpy()]), a.type, domain=a.domain
                )
        return Frame(out)

    # -- split (h2o.split_frame / water.rapids AstSplitFrame) ----------------
    def split_frame(self, ratios: Sequence[float], seed: int = 1234) -> List["Frame"]:
        rng = np.random.default_rng(seed)
        u = rng.random(self.nrow)
        bounds = np.cumsum([0.0] + list(ratios) + [1.0 - sum(ratios)])
        return [self.take(np.nonzero((u >= bounds[i]) & (u < bounds[i + 1]))[0])
                for i in range(len(bounds) - 1)]

    # -- conversion ---------------------------------------------------------
    def to_numpy(self, names: Optional[Sequence[str]] = None) -> np.ndarray:
        names = list(names) if names else self.names
        return np.column_stack([self._vecs[n].numeric_np() for n in names])

    def as_data_frame(self):
        """dict-of-columns (decoded enums), pandas-free."""
        out = {}
        for n, v in self._vecs.items():
            if v.type == "enum":
                dom = np.asarray(v.domain + [None], dtype=object)
                out[n] = dom[np.asarray(v.data)]
            elif v.type == "string":
                out[n] = v.to_numpy()
            else:
                out[n] = v.numeric_np()
        return out

    # -- summaries (Frame.summary / RollupStats) -----------------------------
    def describe(self) -> Dict[str, Dict[str, float]]:
        out = {}
        for n, v in self._vecs.items():
            if v.type == "string":
                out[n] = {"type": "string", "nacnt": v.nacnt()}
            else:
                out[n] = {
                    "type": v.type, "min": v.min(), "max": v.max(),
                    "mean": v.mean(), "sd": v.sd(), "nacnt": v.nacnt(),
                }
        return out

    def asfactor(self, name: Optional[str] = None) -> "Frame":
        """Coerce column(s) to enum (H2OFrame.asfactor)."""
        names = [name] if name else self.names
        out = dict(self._vecs)
        for n in names:
            v = out[n]
            if v.type != "enum":
                out[n] = Vec.from_numpy(np.asarray(v.numeric_np()), "enum")
        return Frame(out)

    # -- munging entry points (water/rapids subset, see rapids.py) -----------
    def group_by(self, by):
        from .rapids import GroupBy

        return GroupBy(self, by)

    def merge(self, other: "Frame", all_x: bool = False, all_y: bool = False,
              by: Optional[Sequence[str]] = None) -> "Frame":
        from .rapids import merge as _merge

        return _merge(self, other, by=by, all_x=all_x, all_y=all_y)

    def quantile(self, prob=None, combine_method: str = "interpolate") -> "Frame":
        from .rapids import quantile as _quantile

        return _quantile(self, prob or [0.01, 0.1, 0.25, 0.333, 0.5, 0.667, 0.75, 0.9, 0.99],
                         combine_method)

    def tokenize(self, split: str = " ") -> "Frame":
        from .text import tokenize as _tok
        return _tok(self, split)

    def table(self) -> "Frame":
        from .rapids import table as _table

        return _table(self)

    # -- elementwise arithmetic/comparison (lazy-ExprNode surface, eager) ----
    def _col0(self) -> np.ndarray:
        return self.vecs()[0].numeric_np()

    def _binop(self, other, op):
        a = self._col0()
        b = other._col0() if isinstance(other, Frame) else other
        return op(a, b)

    def _arith(self, other, op, name):
        return Frame({name: Vec(self._binop(other, op).astype(np.float32), "real")})

    def __add__(self, other):
        return self._arith(other, np.add, self.names[0])

    def __sub__(self, other):
        return self._arith(other, np.subtract, self.names[0])

    def __mul__(self, other):
        return self._arith(other, np.multiply, self.names[0])

    def __truediv__(self, other):
        return self._arith(other, np.divide, self.names[0])

    def __gt__(self, other):
        return self._binop(other, np.greater)

    def __lt__(self, other):
        return self._binop(other, np.less)

    def __ge__(self, other):
        return self._binop(other, np.greater_equal)

    def __le__(self, other):
        return self._binop(other, np.less_equal)

    def __eq__(self, other):  # noqa: comparisons return row masks like H2OFrame
        if isinstance(other, (int, float, np.number, Frame)):
            return self._binop(other, np.equal)
        if isinstance(other, str):
            v = self.vecs()[0]
            if v.type == "enum":
                code = v.domain.index(other) if other in (v.domain or []) else -2
                return np.asarray(v.data) == code
            if v.type == "string":
                return np.asarray([s == other for s in v.to_numpy()])
        return NotImplemented

    def __ne__(self, other):
        eq = self.__eq__(other)
        return NotImplemented if eq is NotImplemented else ~eq

    def __hash__(self):
        return id(self)

    def mean(self):
        return [v.mean() for v in self.vecs()]

    def sum_col(self, name: str) -> float:
        return float(np.nansum(self.vec(name).numeric_np()))

    def __repr__(self):
        return f"Frame({self.nrow}x{self.ncol} {list(self.types.items())[:6]}...)"
