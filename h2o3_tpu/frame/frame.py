"""Frame — distributed columnar table.

Reference parity: `h2o-core/src/main/java/water/fvec/Frame.java`. A Frame is
an ordered set of named `Vec`s of equal length. Unlike the reference (chunks
homed per-node in the DKV, `water/DKV.java`), columns here are dense JAX
arrays; row-sharding over the ``hosts`` mesh axis happens at compute time via
`NamedSharding` (see `h2o3_tpu/parallel/mesh.py`), which is where H2O's
"home node" concept goes on a TPU pod.

Munging surface mirrors the parts of `h2o-py/h2o/frame.py` (H2OFrame) that
the reference's own tests exercise: indexing, split_frame, cbind/rbind,
describe/summary, type coercion. The lazy-ExprNode/Rapids indirection
(`h2o-core/.../water/rapids/`) is collapsed: clients are in-process, so ops
execute eagerly — see `h2o3_tpu/frame/rapids.py` for the expression layer.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from .vec import Vec

_key_counter = itertools.count()


def _python_obj_to_vecs(data, column_names=None, column_types=None):
    """h2o-py `H2OFrame(python_obj)` coercion: pandas DataFrame/Series /
    numpy array / dict of sequences / list of rows → Dict[str, Vec].

    As in h2o-py, a flat list is one ROW (1×n), not a column. pandas
    handling: missing values normalized to None/NaN (pd.NA and NaN-in-object
    would break enum inference), datetimes → ms-since-epoch 'time' vecs,
    labels coerced to str."""
    auto_types: Dict[str, str] = {}
    if hasattr(data, "to_frame") and not hasattr(data, "columns"):
        data = data.to_frame()  # pandas Series → one-column DataFrame
    if hasattr(data, "to_dict") and hasattr(data, "columns") \
            and not isinstance(data, dict):
        import pandas as pd

        cols = {}
        for c in data.columns:
            s = data[c]
            name = str(c)
            if pd.api.types.is_datetime64_any_dtype(s.dtype):
                v = s.to_numpy()
                out = v.astype("datetime64[ms]").astype(np.float64)
                out[np.isnat(v)] = np.nan
                cols[name] = out
                auto_types[name] = "time"
            elif (s.dtype == object
                  or isinstance(s.dtype, pd.CategoricalDtype)
                  or pd.api.types.is_string_dtype(s.dtype)):
                cols[name] = s.astype(object).where(s.notna(), None).to_numpy()
            else:
                cols[name] = s.to_numpy()
        data = cols
    if isinstance(data, dict):
        names = [str(n) for n in data]
        cols = [np.asarray(c) for c in data.values()]
    else:
        # list of rows: optional header row (all-string first row, h2o-py rule)
        if (isinstance(data, (list, tuple)) and data
                and isinstance(data[0], (list, tuple))):
            rows = [list(r) for r in data]
            if (column_names is None
                    and all(isinstance(v, str) for v in rows[0])
                    and len(rows) > 1
                    and not all(isinstance(v, str) for r in rows[1:] for v in r)):
                column_names, rows = rows[0], rows[1:]
            arr = np.asarray(rows, dtype=object)
        else:
            arr = np.atleast_2d(np.asarray(data))
        names = ([str(n) for n in column_names] if column_names
                 else [f"C{i+1}" for i in range(arr.shape[1])])
        cols = [arr[:, i] for i in range(arr.shape[1])]
    # resolve a positional column_types list only after names are known
    if isinstance(column_types, (list, tuple)):
        column_types = {n: t for n, t in zip(names, column_types)}
    types = dict(auto_types)
    types.update({str(k): v for k, v in (column_types or {}).items()})
    return {n: Vec.from_numpy(c, types.get(n)) for n, c in zip(names, cols)}


class Frame:
    def __init__(self, vecs=None, key: Optional[str] = None,
                 column_names: Optional[Sequence[str]] = None,
                 column_types=None, destination_frame: Optional[str] = None,
                 **_h2o_compat):
        """Frame from Dict[str, Vec] (internal) or, matching h2o-py's
        `H2OFrame(python_obj)`, from a pandas DataFrame / numpy array /
        dict of sequences / list of rows."""
        if vecs is None:
            vecs = {}
        client_created = not (isinstance(vecs, dict)
                              and all(isinstance(v, Vec) for v in vecs.values()))
        if client_created:
            vecs = _python_obj_to_vecs(vecs, column_names, column_types)
        lens = {len(v) for v in vecs.values()}
        if len(lens) > 1:
            raise ValueError(f"ragged frame: column lengths {lens}")
        self._vecs: Dict[str, Vec] = dict(vecs)
        self.key = key or destination_frame or f"frame_{next(_key_counter)}"
        if client_created:
            # client-created frames live in the DKV (H2OFrame upload → DKV
            # key) so Rapids expressions and get_frame can resolve them
            from ..runtime.dkv import DKV

            DKV.put(self.key, self)

    # -- construction -------------------------------------------------------
    @staticmethod
    def from_numpy(
        arr: np.ndarray,
        names: Optional[Sequence[str]] = None,
        column_types: Optional[Dict[str, str]] = None,
    ) -> "Frame":
        arr = np.atleast_2d(np.asarray(arr))
        names = list(names) if names else [f"C{i+1}" for i in range(arr.shape[1])]
        column_types = column_types or {}
        return Frame(
            {n: Vec.from_numpy(arr[:, i], column_types.get(n)) for i, n in enumerate(names)}
        )

    @staticmethod
    def from_dict(d: Dict[str, Sequence], column_types: Optional[Dict[str, str]] = None) -> "Frame":
        column_types = column_types or {}
        return Frame(
            {n: Vec.from_numpy(np.asarray(c), column_types.get(n)) for n, c in d.items()}
        )

    # -- shape / metadata ---------------------------------------------------
    @property
    def names(self) -> List[str]:
        return list(self._vecs)

    @property
    def columns(self) -> List[str]:
        return self.names

    @property
    def ncol(self) -> int:
        return len(self._vecs)

    @property
    def nrow(self) -> int:
        return len(next(iter(self._vecs.values()))) if self._vecs else 0

    @property
    def shape(self):
        return (self.nrow, self.ncol)

    @property
    def types(self) -> Dict[str, str]:
        return {n: v.type for n, v in self._vecs.items()}

    def vec(self, name: str) -> Vec:
        return self._vecs[name]

    def vecs(self) -> List[Vec]:
        return list(self._vecs.values())

    # -- indexing -----------------------------------------------------------
    def __getitem__(self, item) -> "Frame":
        # f["col"] / f[["a","b"]] -> column subset
        if isinstance(item, str):
            return Frame({item: self._vecs[item]})
        if isinstance(item, (list, tuple)) and item and all(isinstance(i, str) for i in item):
            return Frame({n: self._vecs[n] for n in item})
        if isinstance(item, (list, tuple)) and item and all(isinstance(i, (int, np.integer)) for i in item):
            names = self.names
            return Frame({names[i]: self._vecs[names[i]] for i in item})
        if isinstance(item, int):
            n = self.names[item]
            return Frame({n: self._vecs[n]})
        # boolean mask / row index array / slice
        if isinstance(item, slice):
            idx = np.arange(self.nrow)[item]
            return self.take(idx)
        if isinstance(item, (np.ndarray, list)):
            idx = np.asarray(item)
            if idx.dtype == bool:
                idx = np.nonzero(idx)[0]
            return self.take(idx)
        if isinstance(item, tuple) and len(item) == 2:
            rows, cols = item
            sub = self[cols] if not isinstance(cols, slice) else self
            return sub[rows] if not isinstance(rows, slice) or rows != slice(None) else sub
        raise TypeError(f"bad index {item!r}")

    def __setitem__(self, name: str, value) -> None:
        if isinstance(value, Frame):
            value = value.vecs()[0]
        if not isinstance(value, Vec):
            value = Vec.from_numpy(np.asarray(value))
        if self._vecs and len(value) != self.nrow:
            raise ValueError("length mismatch")
        self._vecs[name] = value
        self._touch()

    def _touch(self) -> None:
        """In-place mutation hook: every mutator calls this so per-frame
        caches (e.g. stacked-ensemble level-one predictions, the training
        dataset-artifact cache keyed on `_version`) can never serve results
        computed from the frame's previous contents."""
        self.__dict__.pop("_lvl1_preds", None)
        self._version = getattr(self, "_version", 0) + 1

    def take(self, idx: np.ndarray) -> "Frame":
        return Frame({n: v.take(idx) for n, v in self._vecs.items()})

    def drop(self, names: Union[str, Sequence[str]]) -> "Frame":
        if isinstance(names, str):
            names = [names]
        return Frame({n: v for n, v in self._vecs.items() if n not in set(names)})

    # -- combination --------------------------------------------------------
    def cbind(self, other: "Frame") -> "Frame":
        out = dict(self._vecs)
        for n, v in other._vecs.items():
            nn = n
            while nn in out:
                nn = nn + "0"  # h2o dedup convention
            out[nn] = v
        return Frame(out)

    def rbind(self, other: "Frame") -> "Frame":
        return Frame.rbind_all([self, other])

    @staticmethod
    def rbind_all(frames: Sequence["Frame"]) -> "Frame":
        """Stack k frames rowwise with ONE concatenate per column —
        incremental pairwise rbind over a k-file import would copy
        O(k²) rows."""
        if not frames:
            raise ValueError("rbind_all: no frames")
        first = frames[0]
        for fr in frames[1:]:
            if fr.names != first.names:
                raise ValueError("rbind: column names differ")
        out = {}
        for n in first.names:
            vs = [fr._vecs[n] for fr in frames]
            if any(v.type == "enum" for v in vs):
                dom = list(dict.fromkeys(
                    x for v in vs for x in (v.domain or [])))
                parts = []
                for v in vs:
                    dv = v.domain or []
                    remap = (np.asarray([dom.index(x) for x in dv],
                                        np.int32)
                             if dv else np.zeros(0, np.int32))
                    c = np.asarray(v.data)
                    parts.append(np.where(c >= 0,
                                          remap[np.maximum(c, 0)], -1))
                out[n] = Vec(np.concatenate(parts), "enum", domain=dom)
            else:
                out[n] = Vec(
                    np.concatenate([v.to_numpy() for v in vs]),
                    vs[0].type, domain=vs[0].domain)
        return Frame(out)

    # -- split (h2o.split_frame / water.rapids AstSplitFrame) ----------------
    def split_frame(self, ratios: Sequence[float], seed: int = 1234) -> List["Frame"]:
        rng = np.random.default_rng(seed)
        u = rng.random(self.nrow)
        bounds = np.cumsum([0.0] + list(ratios) + [1.0 - sum(ratios)])
        return [self.take(np.nonzero((u >= bounds[i]) & (u < bounds[i + 1]))[0])
                for i in range(len(bounds) - 1)]

    # -- conversion ---------------------------------------------------------
    def to_numpy(self, names: Optional[Sequence[str]] = None) -> np.ndarray:
        names = list(names) if names else self.names
        return np.column_stack([self._vecs[n].numeric_np() for n in names])

    def as_data_frame(self, use_pandas: bool = True):
        """pandas DataFrame (h2o-py default), or dict-of-columns with
        decoded enums when use_pandas=False / pandas is unavailable."""
        out = {}
        for n, v in self._vecs.items():
            if v.type == "enum":
                dom = np.asarray(v.domain + [None], dtype=object)
                out[n] = dom[np.asarray(v.data)]
            elif v.type == "string":
                out[n] = v.to_numpy()
            else:
                out[n] = v.numeric_np()
        if use_pandas:
            try:
                import pandas as pd

                return pd.DataFrame(out)
            except ImportError:
                pass
        return out

    def apply(self, fun, axis: int = 0) -> "Frame":
        """`H2OFrame.apply` — map a python callable over columns (axis=0)
        or rows (axis=1). The h2o-py client compiles lambdas to Rapids
        `{ x . body }` ASTs; in-process the callable runs directly. The
        callable receives a single-column (or single-row) Frame and must
        return a scalar or a Frame."""
        if axis not in (0, 1):
            raise ValueError("axis must be 0 (columns) or 1 (rows)")

        def _normalize(r):
            """callable result → ('col', ndarray) | ('scalar', float).
            Comparison operators on Frames return bare ndarrays, so those
            count as full columns too."""
            if isinstance(r, Frame):
                r = r._col0()
            arr = np.asarray(r, np.float64)
            if arr.ndim >= 1 and arr.size == self.nrow and self.nrow != 1:
                return "col", arr.reshape(-1)
            if arr.size != 1:
                raise ValueError(
                    f"apply: callable returned {arr.size} values; expected "
                    f"a scalar or a full column of {self.nrow}")
            return "scalar", float(arr.reshape(-1)[0])

        def _row_values(r):
            """axis=1 result for ONE row → flat f64 values. A k-value result
            yields k output columns (upstream AstApply row semantics) —
            sizing against self.nrow here would silently misread an
            ncol-sized row result whenever ncol == nrow."""
            if isinstance(r, Frame):
                return np.asarray(
                    [float(r.vec(nm).numeric_np()[0]) for nm in r.names])
            return np.asarray(r, np.float64).reshape(-1)

        if axis == 0:
            out = {}
            reduced = None
            for n in self.names:
                kind, v = _normalize(fun(self[[n]]))
                is_red = kind == "scalar"
                if reduced is None:
                    reduced = is_red
                elif reduced != is_red:
                    raise ValueError(
                        "apply: callable returned a mix of reductions and "
                        "full columns across columns")
                out[n] = np.asarray([v]) if is_red else v
            return Frame.from_dict(out)

        def _rows_loop():
            """The seed per-row path — exact semantics of record: one
            single-row Frame per row through the callable."""
            rows = [_row_values(fun(self.take(np.asarray([i]))))
                    for i in range(self.nrow)]
            widths = {len(r) for r in rows}
            if len(widths) > 1:
                raise ValueError(
                    f"apply: row callable returned ragged widths "
                    f"{sorted(widths)}")
            return np.asarray(rows, np.float64)

        def _rows_vectorized():
            """ONE whole-frame evaluation of the callable: elementwise
            Frame/numpy ops commute with row slicing, so the full-column
            result equals the per-row loop. Acceptance needs THREE
            certificates — the result maps to (nrow, k); the callable
            commutes with a row permutation (row-local functions must,
            sorts/shifts/swaps don't); and probe rows match a real
            per-row evaluation bitwise (catches aggregate-shifted
            results). Anything else falls back to the loop — None then."""
            def _norm(res):
                if isinstance(res, Frame):
                    if res.nrow != self.nrow:
                        return None
                    return np.column_stack(
                        [res.vec(nm).numeric_np() for nm in res.names]
                    ).astype(np.float64)
                arr = np.asarray(res, np.float64)
                if arr.ndim == 1 and arr.shape[0] == self.nrow:
                    return arr.reshape(-1, 1)
                if arr.ndim == 2 and arr.shape[0] == self.nrow:
                    return arr
                return None

            try:
                # trial-eval against COPIES: the seed only ever handed the
                # callable throwaway single-row frames, so a callable that
                # mutates its argument must not corrupt the source frame
                mat = _norm(fun(self.take(np.arange(self.nrow))))
                if mat is None:
                    return None
                # permutation-equivariance: evaluate on shuffled rows and
                # un-shuffle — bitwise equality is required of any
                # row-local callable, and positional mixing (sort, swap,
                # reverse, cumsum) cannot survive it
                perm = np.random.default_rng(0x5EED).permutation(self.nrow)
                mat_p = _norm(fun(self.take(perm)))
                if mat_p is None or mat_p.shape != mat.shape:
                    return None
                inv = np.empty(self.nrow, np.int64)
                inv[perm] = np.arange(self.nrow)
                if not np.array_equal(mat_p[inv], mat, equal_nan=True):
                    return None
            except Exception:
                return None
            # probe ends, interior rows, AND each column's extreme rows: a
            # callable that mixes rows (reverse, cumsum, mean-centering)
            # can coincidentally match at fixed positions, but a row
            # holding a column's min/max disagrees with any aggregate-
            # shifted result unless the column is constant
            n = self.nrow
            probes = {0, n // 3, n // 2, (2 * n) // 3, n - 1}
            for v in self._vecs.values():
                if v.type == "string":
                    continue
                c = v.numeric_np()
                if not np.isnan(c).all():
                    probes.add(int(np.nanargmax(c)))
                    probes.add(int(np.nanargmin(c)))
            if len(probes) >= n:
                # probing every row IS the loop — no vectorized win left
                return None
            for i in sorted(probes):
                try:
                    rv = _row_values(fun(self.take(np.asarray([i]))))
                except Exception:
                    return None
                if rv.shape[0] != mat.shape[1] or not np.array_equal(
                        rv, mat[i], equal_nan=True):
                    return None
            return mat

        from . import munge_stats

        legacy = munge_stats.legacy_enabled()
        with munge_stats.op("apply_rows", self.nrow,
                            path="legacy" if legacy else "vectorized") as _rec:
            # 0-row frames go straight to the loop (its IndexError is the
            # pinned seed behavior) but book as "fallback", not "legacy" —
            # the legacy counter means H2O3_MUNGE_LEGACY=1 only
            arr = None if (legacy or self.nrow == 0) else _rows_vectorized()
            if arr is None:
                if not legacy:
                    _rec["path"] = "fallback"
                arr = _rows_loop()
            _rec["rows_out"] = arr.shape[0]
            # output shaping stays INSIDE the op block: the 0-row
            # IndexError at arr.shape[1] must book as an error, not leave
            # a successful entry behind
            if arr.shape[1] == 1:
                return Frame.from_dict({"apply": arr[:, 0]})
            return Frame.from_dict(
                {f"C{j + 1}": arr[:, j] for j in range(arr.shape[1])})

    # -- summaries (Frame.summary / RollupStats) -----------------------------
    def describe(self) -> Dict[str, Dict[str, float]]:
        out = {}
        for n, v in self._vecs.items():
            if v.type == "string":
                out[n] = {"type": "string", "nacnt": v.nacnt()}
            else:
                out[n] = {
                    "type": v.type, "min": v.min(), "max": v.max(),
                    "mean": v.mean(), "sd": v.sd(), "nacnt": v.nacnt(),
                }
        return out

    def asfactor(self, name: Optional[str] = None) -> "Frame":
        """Coerce column(s) to enum (H2OFrame.asfactor)."""
        names = [name] if name else self.names
        out = dict(self._vecs)
        for n in names:
            v = out[n]
            if v.type != "enum":
                out[n] = Vec.from_numpy(np.asarray(v.numeric_np()), "enum")
        return Frame(out)

    # -- column/type introspection (H2OFrame surface) ------------------------
    def levels(self):
        """Per-column domains for enum columns (H2OFrame.levels)."""
        return [v.domain or [] for v in self.vecs()]

    def nlevels(self):
        return [v.nlevels for v in self.vecs()]

    def isfactor(self):
        return [v.type == "enum" for v in self.vecs()]

    def isnumeric(self):
        return [v.type in ("real", "int") for v in self.vecs()]

    def ischaracter(self):
        return [v.type == "string" for v in self.vecs()]

    def set_names(self, names) -> "Frame":
        names = list(names)
        if len(names) != self.ncol:
            raise ValueError(f"set_names: {len(names)} names for {self.ncol} columns")
        if len(set(names)) != len(names):
            raise ValueError("set_names: duplicate column names")
        self._vecs = dict(zip(names, self._vecs.values()))
        self._touch()
        return self

    def rename(self, columns: Dict[str, str]) -> "Frame":
        """{old: new} column rename (H2OFrame.rename)."""
        new_names = [columns.get(n, n) for n in self._vecs]
        if len(set(new_names)) != len(new_names):
            raise ValueError("rename: would create duplicate column names")
        self._vecs = dict(zip(new_names, self._vecs.values()))
        self._touch()
        return self

    def columns_by_type(self, coltype: str = "numeric"):
        sel = {
            "numeric": lambda v: v.type in ("real", "int"),
            "categorical": lambda v: v.type == "enum",
            "string": lambda v: v.type == "string",
            "time": lambda v: v.type == "time",
        }.get(coltype)
        if sel is None:
            raise ValueError(f"columns_by_type: unknown type {coltype!r}")
        return [float(i) for i, v in enumerate(self.vecs()) if sel(v)]

    # -- munging entry points (water/rapids subset, see rapids.py) -----------
    def group_by(self, by):
        from .rapids import GroupBy

        return GroupBy(self, by)

    def merge(self, other: "Frame", all_x: bool = False, all_y: bool = False,
              by: Optional[Sequence[str]] = None) -> "Frame":
        from .rapids import merge as _merge

        return _merge(self, other, by=by, all_x=all_x, all_y=all_y)

    def quantile(self, prob=None, combine_method: str = "interpolate") -> "Frame":
        from .rapids import quantile as _quantile

        return _quantile(self, prob or [0.01, 0.1, 0.25, 0.333, 0.5, 0.667, 0.75, 0.9, 0.99],
                         combine_method)

    def tokenize(self, split: str = " ") -> "Frame":
        from .text import tokenize as _tok
        return _tok(self, split)

    def table(self) -> "Frame":
        from .rapids import table as _table

        return _table(self)

    # -- wider H2OFrame munging surface (AstImpute/AstScale/AstSort/prims) ---
    def impute(self, column=None, method: str = "mean",
               combine_method: str = "interpolate", by=None) -> "Frame":
        """In-place NA imputation (h2o.impute / AstImpute): mean/median/mode
        for numerics (mode = most frequent value), mode for categoricals;
        `by` imputes within groups of the given column(s)."""
        if method not in ("mean", "median", "mode"):
            raise ValueError(f"impute: unsupported method {method!r}")
        names = ([column] if isinstance(column, str)
                 else list(column) if column else self.names)
        if by is not None:
            by = [by] if isinstance(by, str) else list(by)
            # exact composite keys via row-wise unique — safe for negative /
            # fractional / NA group values
            mat = np.column_stack([self._vecs[b].numeric_np() for b in by])
            _, groups = np.unique(np.nan_to_num(mat, nan=np.inf), axis=0,
                                  return_inverse=True)
            groups = groups.reshape(-1)
        else:
            groups = np.zeros(self.nrow, np.int64)
        # sorted segmentation: one argsort, then per-group contiguous slices
        order = np.argsort(groups, kind="stable")
        sorted_groups = groups[order]
        starts = np.searchsorted(sorted_groups, np.arange(sorted_groups[-1] + 1 if len(sorted_groups) else 0))
        bounds = list(starts) + [len(order)]

        def fill_value(vals):
            if method == "median":
                return np.nanmedian(vals)
            if method == "mode":
                fin = vals[~np.isnan(vals)]
                u, c = np.unique(fin, return_counts=True)
                return u[c.argmax()]
            return np.nanmean(vals)

        for n in names:
            if by and n in by:
                continue
            v = self._vecs[n]
            if v.type == "enum":
                codes = np.asarray(v.data).copy()
                for gi in range(len(bounds) - 1):
                    rows = order[bounds[gi]:bounds[gi + 1]]
                    sub = codes[rows]
                    ok = sub >= 0
                    if (~ok).any() and ok.any():
                        sub[~ok] = np.bincount(sub[ok]).argmax()
                        codes[rows] = sub
                self._vecs[n] = Vec(codes.astype(np.int32), "enum", domain=v.domain)
                self._touch()
            elif v.type != "string":
                col = v.numeric_np().copy()  # never mutate a shared Vec buffer
                for gi in range(len(bounds) - 1):
                    rows = order[bounds[gi]:bounds[gi + 1]]
                    sub = col[rows]
                    na = np.isnan(sub)
                    if na.any() and not na.all():
                        sub[na] = fill_value(sub)
                        col[rows] = sub
                self._vecs[n] = Vec(col.astype(np.float32), v.type)
                self._touch()
        return self

    def scale(self, center=True, scale=True) -> "Frame":
        """Standardize numeric columns (H2OFrame.scale)."""
        out = {}
        for n, v in self._vecs.items():
            if v.type in ("real", "int"):
                col = v.numeric_np()
                mu = np.nanmean(col) if center else 0.0
                sd = np.nanstd(col, ddof=1) if scale else 1.0
                out[n] = Vec(((col - mu) / (sd if sd > 1e-300 else 1.0)
                              ).astype(np.float32), "real")
            else:
                out[n] = v
        return Frame(out)

    def sort(self, by, ascending=True) -> "Frame":
        """Row sort by column(s) (H2OFrame.sort / AstSort radix sort)."""
        by = [by] if isinstance(by, (str, int)) else list(by)
        by = [self.names[b] if isinstance(b, int) else b for b in by]
        asc = ([ascending] * len(by) if isinstance(ascending, bool)
               else list(ascending))
        idx = np.arange(self.nrow)
        for b, a in zip(reversed(by), reversed(asc)):  # stable multi-key
            col = self._vecs[b].numeric_np()[idx]
            order = np.argsort(col if a else -col, kind="mergesort")
            idx = idx[order]
        return self.take(idx)

    def na_omit(self) -> "Frame":
        """Drop rows with any NA (H2OFrame.na_omit)."""
        mask = np.zeros(self.nrow, bool)
        for v in self._vecs.values():
            mask |= v.isna_np()
        return self.take(np.nonzero(~mask)[0])

    def unique(self) -> "Frame":
        v = self.vecs()[0]
        n = self.names[0]
        if v.type == "enum":
            codes = np.asarray(v.data)
            present = sorted(set(codes[codes >= 0]))
            return Frame.from_dict(
                {n: np.asarray([v.domain[i] for i in present], dtype=object)},
                column_types={n: "enum"})
        u = np.unique(v.numeric_np())
        return Frame.from_dict({n: u[~np.isnan(u)]})

    def head(self, rows: int = 10) -> "Frame":
        return self.take(np.arange(min(rows, self.nrow)))

    def tail(self, rows: int = 10) -> "Frame":
        return self.take(np.arange(max(self.nrow - rows, 0), self.nrow))

    def cor(self, na_rm: bool = True) -> np.ndarray:
        """Pearson correlation matrix of the numeric columns (h2o.cor)."""
        cols = [v.numeric_np() for v in self._vecs.values()
                if v.type in ("real", "int")]
        X = np.column_stack(cols)
        if na_rm:
            X = X[~np.isnan(X).any(axis=1)]
        return np.corrcoef(X, rowvar=False)

    def _prim(self, op: str, *args):
        """Delegate an h2o-py Frame convenience to the Rapids interpreter —
        ONE implementation per op, shared with the `/99/Rapids` surface."""
        from .rapids_expr import RapidsSession

        return RapidsSession()._apply_prim(op, [self, *args])

    def cumsum(self) -> "Frame":
        return self._prim("cumsum")

    def cumprod(self) -> "Frame":
        return self._prim("cumprod")

    def cummin(self) -> "Frame":
        return self._prim("cummin")

    def cummax(self) -> "Frame":
        return self._prim("cummax")

    def var(self, na_rm: bool = True):
        """Sample variance of the single numeric column, or the covariance
        matrix of the numeric columns (H2OFrame.var)."""
        num = [v.numeric_np() for v in self._vecs.values()
               if v.type in ("real", "int")]
        if not num:
            raise ValueError("var: frame has no numeric columns")
        if len(num) == 1:
            c = num[0]
            if na_rm:
                c = c[~np.isnan(c)]
            return float(np.var(c, ddof=1)) if len(c) > 1 else float("nan")
        X = np.column_stack(num)
        if na_rm:
            X = X[~np.isnan(X).any(axis=1)]
        return np.cov(X, rowvar=False)

    def kfold_column(self, n_folds: int = 3, seed: int = -1) -> "Frame":
        """Random fold-index column (H2OFrame.kfold_column)."""
        return self._prim("kfold_column", n_folds, seed)

    def modulo_kfold_column(self, n_folds: int = 3) -> "Frame":
        return self._prim("modulo_kfold_column", n_folds)

    def stratified_kfold_column(self, n_folds: int = 3,
                                seed: int = -1) -> "Frame":
        """Fold column preserving per-class ratios
        (H2OFrame.stratified_kfold_column; the response is this frame's
        single categorical column)."""
        return self._prim("stratified_kfold_column", n_folds, seed)

    def relevel(self, y: str) -> "Frame":
        """Make `y` the reference (first) level of this 1-column
        categorical frame (H2OFrame.relevel)."""
        return self._prim("relevel", y)

    def difflag1(self) -> "Frame":
        """First-order difference with a leading NA (H2OFrame.difflag1)."""
        return self._prim("difflag1")

    def distance(self, y: "Frame", measure: str = "l2") -> "Frame":
        """Pairwise row distances self × y (H2OFrame.distance:
        l1/l2/cosine/cosine_sq)."""
        return self._prim("distance", y, measure)

    def rank_within_group_by(self, group_by_cols, sort_cols,
                             ascending=None, new_col_name="New_Rank_column",
                             sort_cols_sorted: bool = False) -> "Frame":
        """Row rank within groups following a sort order
        (H2OFrame.rank_within_group_by / AstRankWithinGroupBy)."""
        def _idx(cols):
            return [self.names.index(c) if isinstance(c, str) else int(c)
                    for c in (cols if isinstance(cols, (list, tuple))
                              else [cols])]

        asc = ([bool(b) for b in ascending]
               if ascending is not None else [])
        return self._prim("rank_within_groupby", _idx(group_by_cols),
                          _idx(sort_cols), asc, new_col_name,
                          sort_cols_sorted)

    def melt(self, id_vars, value_vars=None, var_name: str = "variable",
             value_name: str = "value", skipna: bool = False) -> "Frame":
        """Wide → long (H2OFrame.melt / AstMelt)."""
        from . import rapids as rapids_ops

        return rapids_ops.melt(self, list(id_vars),
                               list(value_vars) if value_vars else None,
                               var_name, value_name, skipna)

    def pivot(self, index: str, column: str, value: str) -> "Frame":
        """Long → wide (H2OFrame.pivot / AstPivot)."""
        from . import rapids as rapids_ops

        return rapids_ops.pivot(self, index, column, value)

    def drop_duplicates(self, columns=None, keep: str = "first") -> "Frame":
        """Rows deduplicated by the given columns (all by default),
        keeping the first or last occurrence (H2OFrame.drop_duplicates /
        AstDropDuplicates)."""
        cols = ([self.names.index(c) if isinstance(c, str) else int(c)
                 for c in columns] if columns
                else list(range(self.ncol)))
        return self._prim("drop_duplicates", cols, keep)

    def cut(self, breaks, labels=None, include_lowest: bool = False,
            right: bool = True) -> "Frame":
        """Numeric → categorical binning (H2OFrame.cut / AstCut)."""
        col = self._col0()
        br = np.asarray(breaks, np.float64)
        codes = np.digitize(col, br, right=right) - 1
        oob = (codes < 0) | (codes >= len(br) - 1) | np.isnan(col)
        if include_lowest:
            codes = np.where(col == br[0], 0, codes)
            oob &= ~(col == br[0])
        dom = (list(labels) if labels is not None else
               [f"({br[i]:g},{br[i+1]:g}]" for i in range(len(br) - 1)])
        codes = np.where(oob, -1, codes).astype(np.int32)
        return Frame({self.names[0]: Vec(codes, "enum", domain=dom)})

    # time ops (water/rapids/ast/prims/time/*) — epoch-millis "time" columns
    def _dt64(self):
        """(datetime64[ms] values, na_mask) of the first column."""
        col = self._col0()
        return col.astype("datetime64[ms]"), np.isnan(col)

    def _time_part(self, fn) -> "Frame":
        dt, na = self._dt64()
        vals = fn(dt).astype(np.float64)
        return Frame.from_dict({self.names[0]: np.where(na, np.nan, vals)})

    def year(self) -> "Frame":
        return self._time_part(lambda d: 1970 + d.astype("datetime64[Y]").astype(np.int64))

    def month(self) -> "Frame":
        return self._time_part(
            lambda d: (d.astype("datetime64[M]")
                       - d.astype("datetime64[Y]")).astype(np.int64) + 1)

    def day(self) -> "Frame":
        return self._time_part(
            lambda d: (d.astype("datetime64[D]")
                       - d.astype("datetime64[M]")).astype(np.int64) + 1)

    def hour(self) -> "Frame":
        return self._time_part(
            lambda d: (d - d.astype("datetime64[D]")).astype("timedelta64[h]").astype(np.int64))

    def minute(self) -> "Frame":
        return self._time_part(
            lambda d: ((d - d.astype("datetime64[h]"))
                       .astype("timedelta64[m]").astype(np.int64)))

    def second(self) -> "Frame":
        return self._time_part(
            lambda d: ((d - d.astype("datetime64[m]"))
                       .astype("timedelta64[s]").astype(np.int64)))

    def dayOfWeek(self) -> "Frame":
        # epoch day 0 = Thursday; Monday = 0 (h2o's Mon-first ordering)
        return self._time_part(
            lambda d: (d.astype("datetime64[D]").astype(np.int64) + 3) % 7)

    day_of_week = dayOfWeek

    def hist(self, breaks=20, plot: bool = False) -> "Frame":
        """Histogram table: breaks/counts/mids (H2OFrame.hist, AstHist)."""
        col = self._col0()
        fin = col[~np.isnan(col)]
        if fin.size == 0:
            return Frame.from_dict({"breaks": np.zeros(0), "counts": np.zeros(0),
                                    "mids": np.zeros(0)})
        if isinstance(breaks, int):
            edges = np.linspace(fin.min(), fin.max(), breaks + 1)
        else:
            edges = np.asarray(breaks, np.float64)
        counts, edges = np.histogram(fin, bins=edges)
        return Frame.from_dict({
            "breaks": edges[1:],
            "counts": counts.astype(np.float64),
            "mids": (edges[:-1] + edges[1:]) / 2.0,
        })

    # string ops (water/rapids/ast/prims/string/*) — enum/string columns
    def _string_rows(self):
        """First column as a list of python strings (None for NA) — shared
        row-wise access for the string prims."""
        v = self.vecs()[0]
        if v.type == "string":
            return list(v.to_numpy())
        if v.type == "enum":
            codes = np.asarray(v.data)
            dom = v.domain or []
            return [None if c < 0 or c >= len(dom) else dom[c]
                    for c in codes]
        return [None if x != x else str(x) for x in v.numeric_np()]

    def _map_strings(self, fn) -> "Frame":
        out = {}
        for n, v in self._vecs.items():
            if v.type == "string":
                s = np.asarray([None if x is None else fn(str(x))
                                for x in v.to_numpy()], dtype=object)
                out[n] = Vec(None, "string", strings=s)
            elif v.type == "enum":
                out[n] = Vec(np.asarray(v.data), "enum",
                             domain=[fn(str(d)) for d in (v.domain or [])])
            else:
                out[n] = v
        return Frame(out)

    def sub(self, pattern: str, replacement: str, ignore_case=False) -> "Frame":
        import re
        fl = re.IGNORECASE if ignore_case else 0
        return self._map_strings(lambda s: re.sub(pattern, replacement, s, count=1, flags=fl))

    def gsub(self, pattern: str, replacement: str, ignore_case=False) -> "Frame":
        import re
        fl = re.IGNORECASE if ignore_case else 0
        return self._map_strings(lambda s: re.sub(pattern, replacement, s, flags=fl))

    def trim(self) -> "Frame":
        return self._map_strings(str.strip)

    def tolower(self) -> "Frame":
        return self._map_strings(str.lower)

    def toupper(self) -> "Frame":
        return self._map_strings(str.upper)

    def substring(self, start_index: int, end_index: Optional[int] = None) -> "Frame":
        return self._map_strings(lambda s: s[start_index:end_index])

    def nchar(self) -> "Frame":
        v = self.vecs()[0]
        if v.type == "enum":
            lens = np.asarray([len(d) for d in (v.domain or [])] + [0], np.float64)
            codes = np.asarray(v.data)
            out = np.where(codes >= 0, lens[np.maximum(codes, 0)], np.nan)
        else:
            out = np.asarray([np.nan if s is None else len(str(s))
                              for s in v.to_numpy()], np.float64)
        return Frame.from_dict({self.names[0]: out})

    def strsplit(self, pattern: str) -> "Frame":
        """Split the (single) string column; output columns C1..Ck."""
        import re
        v = self.vecs()[0]
        rows = [([] if s is None else re.split(pattern, str(s)))
                for s in (v.to_numpy() if v.type == "string"
                          else [None if c < 0 else v.domain[c]
                                for c in np.asarray(self.vecs()[0].data)])]
        k = max((len(r) for r in rows), default=0)
        cols = {}
        for j in range(k):
            cols[f"C{j+1}"] = np.asarray(
                [r[j] if j < len(r) else None for r in rows], dtype=object)
        return Frame({n: Vec(None, "string", strings=c) for n, c in cols.items()})

    def ifelse(self, yes, no) -> "Frame":
        """Element-wise conditional on this (boolean/0-1) column:
        `cond.ifelse(yes, no)` (H2OFrame.ifelse / AstIfElse)."""
        return self._prim("ifelse", yes, no)

    def lstrip(self, set: str = " ") -> "Frame":
        """Strip leading characters (H2OFrame.lstrip / AstStrip)."""
        return self._prim("lstrip", set)

    def rstrip(self, set: str = " ") -> "Frame":
        return self._prim("rstrip", set)

    def entropy(self) -> "Frame":
        """Per-string Shannon entropy (H2OFrame.entropy / AstEntropy)."""
        return self._prim("entropy")

    def num_valid_substrings(self, path_to_words: str) -> "Frame":
        """Distinct substrings (length >= 2) present in the words file
        (H2OFrame.num_valid_substrings / AstCountSubstringsWords)."""
        return self._prim("num_valid_substrings", path_to_words)

    def grep(self, pattern: str, ignore_case: bool = False,
             invert: bool = False, output_logical: bool = False) -> "Frame":
        """Matching rows of the (single) string column as a 0/1 column or
        index list (H2OFrame.grep — the Rapids `grep` prim; NA rows count
        as non-matches, so invert=True includes them, like `h2o.grep`)."""
        import re

        flags = re.IGNORECASE if ignore_case else 0
        hit = np.asarray([
            0.0 if s is None else float(bool(re.search(pattern, s, flags)))
            for s in self._string_rows()], np.float64)
        if invert:
            hit = 1.0 - hit
        if output_logical:
            return Frame.from_dict({"grep": hit})
        return Frame.from_dict(
            {"grep": np.nonzero(hit > 0)[0].astype(np.float64)})

    def ascharacter(self) -> "Frame":
        """Every column → string (H2OFrame.ascharacter): categorical codes
        decode through their domain (NA-safe), numerics stringify."""
        out = {}
        for n, v in self._vecs.items():
            rows = Frame({n: v})._string_rows()
            out[n] = Vec(None, "string",
                         strings=np.asarray(rows, dtype=object))
        return Frame(out)

    def countmatches(self, pattern) -> "Frame":
        pats = [pattern] if isinstance(pattern, str) else list(pattern)
        v = self.vecs()[0]
        strs = (v.to_numpy() if v.type == "string"
                else [None if c < 0 else v.domain[c] for c in np.asarray(v.data)])
        out = np.asarray(
            [np.nan if s is None else float(sum(str(s).count(p) for p in pats))
             for s in strs], np.float64)
        return Frame.from_dict({self.names[0]: out})

    # -- elementwise arithmetic/comparison (lazy-ExprNode surface, eager) ----
    def _col0(self) -> np.ndarray:
        return self.vecs()[0].numeric_np()

    def _binop(self, other, op):
        a = self._col0()
        b = other._col0() if isinstance(other, Frame) else other
        return op(a, b)

    def _arith(self, other, op, name):
        if self.ncol > 1:
            # h2o-py semantics: arithmetic maps over ALL columns; a 1-col
            # frame or scalar broadcasts, an equal-width frame is pairwise
            if isinstance(other, Frame) and other.ncol == self.ncol:
                pairs = zip(self.names, other.names)
                return Frame({n: Vec(op(self.vec(n).numeric_np(),
                                        other.vec(m).numeric_np()
                                        ).astype(np.float32), "real")
                              for n, m in pairs})
            b = other._col0() if isinstance(other, Frame) else other
            return Frame({n: Vec(op(self.vec(n).numeric_np(), b
                                    ).astype(np.float32), "real")
                          for n in self.names})
        return Frame({name: Vec(self._binop(other, op).astype(np.float32), "real")})

    def __add__(self, other):
        return self._arith(other, np.add, self.names[0])

    def __sub__(self, other):
        return self._arith(other, np.subtract, self.names[0])

    def __mul__(self, other):
        return self._arith(other, np.multiply, self.names[0])

    def __truediv__(self, other):
        return self._arith(other, np.divide, self.names[0])

    def __gt__(self, other):
        return self._binop(other, np.greater)

    def __lt__(self, other):
        return self._binop(other, np.less)

    def __ge__(self, other):
        return self._binop(other, np.greater_equal)

    def __le__(self, other):
        return self._binop(other, np.less_equal)

    def __eq__(self, other):  # noqa: comparisons return row masks like H2OFrame
        if isinstance(other, (int, float, np.number, Frame)):
            return self._binop(other, np.equal)
        if isinstance(other, str):
            v = self.vecs()[0]
            if v.type == "enum":
                code = v.domain.index(other) if other in (v.domain or []) else -2
                return np.asarray(v.data) == code
            if v.type == "string":
                return np.asarray([s == other for s in v.to_numpy()])
        return NotImplemented

    def __ne__(self, other):
        eq = self.__eq__(other)
        return NotImplemented if eq is NotImplemented else ~eq

    def __hash__(self):
        return id(self)

    def mean(self):
        return [v.mean() for v in self.vecs()]

    def sum_col(self, name: str) -> float:
        return float(np.nansum(self.vec(name).numeric_np()))

    def __repr__(self):
        return f"Frame({self.nrow}x{self.ncol} {list(self.types.items())[:6]}...)"


def frame_to_csv(fr: "Frame") -> str:
    """Frame → CSV text with proper quoting — ONE serializer shared by
    `/3/DownloadDataset` and the remote client's upload path (divergent
    copies would produce CSV round-trip asymmetry)."""
    import csv as _csv
    import io

    buf = io.StringIO()
    w = _csv.writer(buf)
    w.writerow(fr.names)
    cols = fr.as_data_frame(use_pandas=False)
    for n in fr.names:
        col = cols[n]
        if len(col) and any(
                isinstance(v, str) and ("\n" in v or "\r" in v)
                for v in col):
            # the parser (and the distributed byte-range splitter — like
            # the reference's) is line-oriented: a quoted embedded newline
            # cannot round-trip, so refuse loudly instead of corrupting
            raise ValueError(
                f"column {n!r} contains embedded newlines; CSV "
                "serialization is line-oriented (strip them first)")
    mats = [cols[n] for n in fr.names]
    for i in range(fr.nrow):
        w.writerow([
            "" if v is None or (isinstance(v, float) and np.isnan(v))
            else v for v in (m[i] for m in mats)])
    return buf.getvalue()
