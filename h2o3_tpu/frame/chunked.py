"""Parallel chunked CSV tokenizer — the in-process half of ingest.

Reference parity: `water/parser/ParseDataset.java` runs `MultiFileParseTask`
as a multithreaded MRTask over byte ranges, so one node saturates every core
while parsing; `water/parser/CsvParser.java` is the per-chunk tokenizer it
drives. Here the same shape: a process's byte payload (the whole file in
single-process mode, this process's byte range under
`distributed_parse.py`) is split into cache-sized chunks at RFC-4180-safe
line boundaries, chunks tokenize concurrently on a `ThreadPoolExecutor`
(the numpy string ufuncs, the float casts, and the native ctypes tokenizer
all release the GIL), and the per-chunk token matrices concatenate in file
order, so downstream coercion and the phase-2 categorical merge see exactly
the token stream a single-chunk parse would produce.

Boundary rules (the `byte_range` / first-line-after-start semantics of
distributed_parse.py, extended with quote healing):

- a chunk ends immediately after a ``\\n`` byte, so no chunk starts
  mid-line;
- a ``\\n`` preceded by an ODD number of ``"`` bytes is inside an open
  RFC-4180 quoted field (an escaped ``""`` contributes two quotes, which
  preserves the parity invariant) and is never chosen as a boundary — a
  quoted field containing the separator or an embedded newline lands whole
  inside one chunk and tokenizes exactly like the single-chunk path.

Inside a chunk, the per-line `str.split`/`csv.reader` loop is replaced by
one bulk pass: lines with no quote character and exactly ``ncol`` fields
(the overwhelmingly common case) are joined and split ONCE, stripped with
vectorized string ufuncs, and reshaped to an (nrows, ncol) token matrix;
only quoted or ragged lines fall back to the per-line reader, with results
spliced back in row order so semantics stay bit-identical to
`parse._split_lines`.
"""

from __future__ import annotations

import csv as _csv
import os
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Sequence, Tuple

import numpy as np

# L2-cache-scale working set per chunk: big enough to amortize per-chunk
# setup, small enough that several chunks are in flight even for modest files
DEFAULT_CHUNK_BYTES = 4 << 20

# numpy ≥2.0 ships ufunc-backed string ops (np.strings) that run at C speed
# and release the GIL; np.char is the semantically identical slow fallback
_S = np.strings if hasattr(np, "strings") else np.char


def default_nthreads() -> int:
    env = os.environ.get("H2O3_PARSE_THREADS", "")
    if env.strip():
        return max(1, int(env))
    return max(1, os.cpu_count() or 1)


def default_chunk_bytes() -> int:
    env = os.environ.get("H2O3_PARSE_CHUNK_BYTES", "")
    if env.strip():
        return max(1, int(env))
    return DEFAULT_CHUNK_BYTES


def plan_chunks(data: bytes, target_bytes: Optional[int] = None
                ) -> List[Tuple[int, int]]:
    """Split `data` into ~target-sized [lo, hi) chunks that end right after
    a newline byte whose preceding quote count is even (i.e. a real record
    boundary, never the inside of an RFC-4180 quoted field)."""
    n = len(data)
    target = target_bytes or default_chunk_bytes()
    if n <= target:
        return [(0, n)] if n else []
    buf = np.frombuffer(data, dtype=np.uint8)
    nl = np.flatnonzero(buf == 0x0A)          # b"\n"
    if nl.size:
        q = np.flatnonzero(buf == 0x22)       # b'"'
        if q.size:
            # quotes-before-newline parity: odd ⇒ the newline is embedded in
            # an open quoted field ⇒ not a legal cut point
            nl = nl[np.searchsorted(q, nl) % 2 == 0]
    if nl.size == 0:
        return [(0, n)]
    bounds = nl + 1                            # cut AFTER the newline
    targets = np.arange(target, n, target)
    idx = np.searchsorted(bounds, targets)     # first boundary ≥ each target
    cuts = np.unique(bounds[idx[idx < bounds.size]])
    cuts = cuts[cuts < n]
    edges = [0] + [int(c) for c in cuts] + [n]
    return list(zip(edges[:-1], edges[1:]))


def split_csv_line(ln: str, sep: str) -> List[str]:
    """One line's tokens with `parse._split_lines` dispatch semantics:
    lines holding a double quote take the RFC-4180 csv reader (quoted
    cells may hold the separator; quoting preserves edge whitespace and
    literal quotes), everything else the fast plain split with
    strip-spaces-then-quotes. The single shared implementation behind the
    generic tokenizer, the fast path's rare-line patcher, and the
    parse_setup sampler — quote/strip semantics cannot drift apart."""
    if '"' in ln:
        return next(_csv.reader([ln], delimiter=sep))
    return [p.strip().strip('"') for p in ln.split(sep)]


def _tokens_need_strip(joined: str, sep: str) -> bool:
    """Can any token of the joined bulk lines carry edge whitespace?
    Conservative but exact-when-False: bulk lines hold no quote character
    and no line-break class character (splitlines consumed those), so a
    token edge is either adjacent to `sep` or at the ends of `joined`
    (line edges land next to the joining `sep`). Non-ASCII text gets
    `True` wholesale rather than enumerating the unicode space classes."""
    if not joined:
        return False
    if not joined.isascii():
        return True
    if joined[0].isspace() or joined[-1].isspace():
        return True
    for ws in (" ", "\t", "\x0b", "\f"):
        if (ws + sep) in joined or (sep + ws) in joined:
            return True
    return False


def tokenize_block(lines: Sequence[str], sep: str, ncol: int) -> np.ndarray:
    """Tokenize non-blank lines into an (nrows, ncol) object matrix with
    `parse._split_lines` semantics: quoted lines through the RFC-4180 csv
    reader (dequoted, whitespace preserved), plain lines split + stripped
    of spaces then quotes, short rows padded with "", extra fields dropped.
    """
    nrows = len(lines)
    out = np.empty((nrows, ncol), dtype=object)
    if nrows == 0:
        return out
    lens = [len(ln) for ln in lines]
    # np.asarray(lines) materializes an (nrows × longest-line) fixed-width
    # unicode matrix; a chunk mixing many short rows with one very long
    # field would over-allocate max_len/mean_len-fold (e.g. one 1 MB cell
    # among 10k 40-byte rows ⇒ ~40 GB). When the skew makes the matrix
    # cost several× the actual text, classify lines row-wise instead —
    # same bulk mask, O(total chars) memory.
    if max(lens) * nrows > 4 * sum(lens) + (1 << 20):
        bulk = np.fromiter(
            (('"' not in ln) and ln.count(sep) == ncol - 1 for ln in lines),
            np.bool_, nrows)
    else:
        u = np.asarray(lines)
        bulk = (_S.find(u, '"') < 0) & (_S.count(u, sep) == ncol - 1)
    bulk_idx = np.flatnonzero(bulk)
    if bulk_idx.size:
        if bulk_idx.size == nrows:
            joined = sep.join(lines)
        else:
            joined = sep.join([lines[i] for i in bulk_idx])
        # each bulk line holds exactly ncol-1 separators, so one global
        # split yields nrows·ncol tokens that reshape back row-major;
        # the strip pass (and the always-no-op quote strip — bulk lines
        # hold no quote) is elided when no token can have edge whitespace
        toks = joined.split(sep)
        if _tokens_need_strip(joined, sep):
            toks = [t.strip().strip('"') for t in toks]
        out[bulk_idx, :] = np.asarray(toks, dtype=object).reshape(-1, ncol)
    if bulk_idx.size != nrows:
        for i in np.flatnonzero(~bulk):
            parts = split_csv_line(lines[i], sep)
            np_ = len(parts)
            out[i, :] = (parts[:ncol] if np_ >= ncol
                         else parts + [""] * (ncol - np_))
    return out


def _chunk_text_to_block(text: str, sep: str, ncol: int,
                         drop_first_line: bool) -> np.ndarray:
    lines = text.splitlines()
    if drop_first_line:
        lines = lines[1:]
    lines = [ln for ln in lines if ln.strip()]
    return tokenize_block(lines, sep, ncol)


# columns wider than this would make the offset-gather index matrices (and
# the fixed-width unicode columns) memory-heavy; such chunks take the
# generic object-token path instead
_MAX_FAST_TOKEN_W = 256


def _fast_chunk_columns(chunk: bytes, sep: str, ncol: int,
                        drop_first_line: bool) -> Optional[List[np.ndarray]]:
    """Offset tokenizer — the zero-python-object fast path of the chunked
    pipeline. Token [start, end) byte offsets are derived with searchsorted
    algebra over the separator/newline positions, gathered into per-column
    fixed-width byte matrices, and viewed as unicode columns: no python
    str objects exist for the (overwhelmingly common) plain lines, and
    every step is a GIL-releasing numpy kernel, so chunk workers scale on
    real cores. Rare quoted/ragged lines are tokenized per line and
    row-patched into the columns with `_split_lines` semantics.

    Returns per-column ``U`` arrays, or None when the chunk needs the
    generic text path: non-ASCII bytes (multi-byte code points, exotic
    unicode line breaks / space classes), control bytes that
    `str.splitlines` treats as line breaks, NUL bytes, a multi-byte
    separator, or pathologically wide tokens."""
    sep_b = sep.encode() if len(sep) == 1 else b""
    if len(sep_b) != 1:
        return None
    b = np.frombuffer(chunk, dtype=np.uint8)
    if b.size == 0:
        return [np.empty(0, dtype="S1") for _ in range(ncol)]
    if (b >= 0x80).any() or np.isin(
            b, (0x00, 0x0B, 0x0C, 0x1C, 0x1D, 0x1E)).any():
        return None
    # a \r NOT followed by \n is a line break for str.splitlines but not
    # for this byte scan — such chunks take the generic path
    crpos = np.flatnonzero(b == 0x0D)
    if crpos.size and (crpos[-1] == b.size - 1
                       or not bool(np.all(b[crpos + 1] == 0x0A))):
        return None
    nl = np.flatnonzero(b == 0x0A)
    starts = np.concatenate([[0], nl + 1])
    ends = np.concatenate([nl, [b.size]])
    # CRLF: the \r belongs to the terminator, not the line content
    has_content = ends > starts
    cr = np.zeros(len(ends), bool)
    cr[has_content] = b[ends[has_content] - 1] == 0x0D
    ends = ends - cr
    if drop_first_line:
        starts, ends = starts[1:], ends[1:]
    # blank filter ≡ `ln.strip()`: a line of only space/tab is dropped
    # (\r needs no slot — line ends are already trimmed past it).
    # whitespace positions are sparse in data files, so searchsorted over
    # them beats a full-chunk cumsum
    wpos = np.flatnonzero((b == 0x20) | (b == 0x09))
    ws_in_line = np.searchsorted(wpos, ends) - np.searchsorted(wpos, starts)
    blank = ws_in_line == (ends - starts)
    starts, ends = starts[~blank], ends[~blank]
    nrows = len(starts)
    if nrows == 0:
        return [np.empty(0, dtype="S1") for _ in range(ncol)]
    qpos = np.flatnonzero(b == 0x22)
    has_q = (np.searchsorted(qpos, ends)
             - np.searchsorted(qpos, starts)) > 0
    sp = np.flatnonzero(b == sep_b[0])
    a_i = np.searchsorted(sp, starts)
    nsep = np.searchsorted(sp, ends) - a_i
    ok = ~has_q & (nsep == ncol - 1)
    ok_rows = np.flatnonzero(ok)
    bad_rows = np.flatnonzero(~ok)
    if ncol > 1 and ok_rows.size:
        smat = sp[a_i[ok][:, None] + np.arange(ncol - 1)[None, :]]
        tok_s = np.concatenate([starts[ok][:, None], smat + 1], axis=1)
        tok_e = np.concatenate([smat, ends[ok][:, None]], axis=1)
    else:
        tok_s = starts[ok][:, None]
        tok_e = ends[ok][:, None]
    lens = (tok_e - tok_s).astype(np.int32)
    if ok_rows.size and int(lens.max()) > _MAX_FAST_TOKEN_W:
        return None
    # the rare quoted/ragged lines: per-line tokens, patched in below
    bad_toks = []
    for i in bad_rows:
        parts = split_csv_line(
            chunk[starts[i]:ends[i]].decode(), sep)   # ASCII by the gate
        if len(parts) < ncol:
            parts = parts + [""] * (ncol - len(parts))
        bad_toks.append(parts[:ncol])
    bad_w = (max(len(t) for row in bad_toks for t in row)
             if bad_toks else 0)
    if bad_w > _MAX_FAST_TOKEN_W:
        return None
    tok_s = tok_s.astype(np.int32)
    # does ANY plain token carry edge whitespace? whitespace strips only at
    # token edges, i.e. where a ws byte neighbours a separator/newline/CR
    # or the chunk bounds — vectorized over the (sparse) ws positions.
    # When none does, the per-token strip is provably a no-op and elided
    # (the quote strip always is: ok lines hold no quote; ASCII gating
    # keeps str.strip's unicode space classes out of play).
    if wpos.size:
        edge = np.isin(b[np.minimum(wpos + 1, b.size - 1)],
                       (sep_b[0], 0x0A, 0x0D))
        edge |= np.isin(b[np.maximum(wpos - 1, 0)], (sep_b[0], 0x0A))
        edge |= (wpos == 0) | (wpos == b.size - 1)
        needs_strip = bool(edge.any())
    else:
        needs_strip = False
    w_max = int(lens.max()) if ok_rows.size else 1
    # the per-column gather width below also covers bad-row tokens — a
    # quoted cell wider than every plain token must widen the pad too,
    # or the gather indexes past the buffer
    w_max = max(w_max, bad_w, 1)
    bp = np.concatenate([b, np.zeros(w_max, np.uint8)])  # overrun pad
    cols: List[np.ndarray] = []
    span = np.arange(0, 1, dtype=np.int32)
    for c in range(ncol):
        w = int(lens[:, c].max()) if ok_rows.size else 0
        if bad_toks:
            w = max(w, max(len(row[c]) for row in bad_toks))
        w = max(w, 1)
        # columns stay BYTES (S): the chunk is ASCII-gated, S→float64 and
        # S unique/sort run ~2× faster than their UCS4 equivalents, and
        # byte order equals ASCII code-point order so domains sort the same
        col = np.zeros(nrows, dtype=f"S{w}")
        if ok_rows.size:
            if len(span) != w:
                span = np.arange(w, dtype=np.int32)
            m = bp[tok_s[:, c, None] + span[None, :]]
            m[span[None, :] >= lens[:, c, None]] = 0
            toks = m.view(f"S{w}").ravel()
            if needs_strip:
                toks = _S.strip(toks)
            col[ok_rows] = toks
        for j, i in enumerate(bad_rows):
            col[i] = bad_toks[j][c]
        cols.append(col)
    return cols


def _run(fn, idxs, nthreads: int) -> list:
    if nthreads <= 1 or len(idxs) <= 1:
        return [fn(i) for i in idxs]
    with ThreadPoolExecutor(max_workers=min(nthreads, len(idxs))) as ex:
        return list(ex.map(fn, idxs))


def tokenize_data(
    data: bytes,
    sep: str,
    header: bool,
    ncol: int,
    nthreads: Optional[int] = None,
    chunk_bytes: Optional[int] = None,
    use_native: bool = True,
) -> Tuple[List[np.ndarray], dict]:
    """Phase-1 tokenize of a byte payload → per-column arrays + run facts.

    Tries the native per-chunk numeric tokenizer first (all-or-nothing:
    every chunk must parse fully numeric, mirroring the whole-file native
    semantics — a single non-numeric chunk would otherwise mix float and
    token columns and corrupt the categorical intern). Falls back to the
    vectorized python tokenizer per chunk. Returns (columns, info) where
    columns are object token arrays (or float64 on the native path) and
    info = {n_chunks, n_threads, native}.

    The payload is accounted to the memory ledger (`ingest:` owner) for
    the duration of the tokenize, so a parse burst shows up in
    `GET /3/Memory` / the pressure signal while the buffers are live.
    """
    from ..runtime.memory_ledger import ingest_buffer

    with ingest_buffer(len(data)):
        return _tokenize_data_impl(data, sep, header, ncol, nthreads,
                                   chunk_bytes, use_native)


def _tokenize_data_impl(
    data: bytes,
    sep: str,
    header: bool,
    ncol: int,
    nthreads: Optional[int],
    chunk_bytes: Optional[int],
    use_native: bool,
) -> Tuple[List[np.ndarray], dict]:
    nthreads = nthreads if nthreads is not None else default_nthreads()
    chunks = plan_chunks(data, chunk_bytes)
    info = dict(n_chunks=len(chunks), n_threads=min(nthreads,
                                                    max(len(chunks), 1)),
                native=False)
    if not chunks:
        return [np.empty(0, dtype=object) for _ in range(ncol)], info
    # the native field loop is quote-blind (it would split a quoted numeric
    # like "1,234" at the embedded separator and silently mis-column the
    # row) — any quote byte in the payload routes around it
    if use_native and b'"' not in data:
        from ..native import loader as native_loader  # late; optional .so

        if native_loader.available():
            def _nat(i):
                lo, hi = chunks[i]
                return native_loader.tokenize_chunk_numeric(
                    data, lo, hi, sep, ncol, header and i == 0)

            mats = _run(_nat, range(len(chunks)), nthreads)
            if all(m is not None for m in mats):
                mat = (mats[0] if len(mats) == 1
                       else np.concatenate(mats, axis=0))
                info["native"] = True
                return [mat[:, c] for c in range(ncol)], info

    def _py(i):
        lo, hi = chunks[i]
        chunk = data[lo:hi]
        fast = _fast_chunk_columns(chunk, sep, ncol, header and i == 0)
        if fast is not None:
            return fast
        text = chunk.decode("utf-8", errors="replace")
        mat = _chunk_text_to_block(text, sep, ncol, header and i == 0)
        return [mat[:, c] for c in range(ncol)]

    mats = _run(_py, range(len(chunks)), nthreads)
    if len(mats) == 1:
        return mats[0], info
    # fast chunks yield bytes (S) columns, generic chunks object-of-str;
    # mixing would corrupt comparisons, so when any chunk went generic the
    # S columns widen to unicode first (S+U concat then promotes to object
    # holding str-likes throughout). All-fast stays S, widths widening to
    # the max via np.concatenate's dtype promotion.
    mixed = any(m[0].dtype.kind == "O" for m in mats)
    out = []
    for c in range(ncol):
        parts = [m[c] for m in mats]
        if mixed:
            parts = [p.astype("U") if p.dtype.kind == "S" else p
                     for p in parts]
        out.append(np.concatenate(parts))
    return out, info


def tokenize_lines(
    lines: Sequence[str],
    sep: str,
    ncol: int,
    nthreads: Optional[int] = None,
    block_rows: Optional[int] = None,
) -> Tuple[List[np.ndarray], dict]:
    """Tokenize pre-split lines (the distributed byte-range path, where
    `read_range_lines` already owns the cross-process boundary semantics)
    in parallel row blocks. Block membership cannot change per-line
    results, so the output is bit-identical to one whole-list pass.
    Returns (columns, info) like `tokenize_data`."""
    n = len(lines)
    nthreads = nthreads if nthreads is not None else default_nthreads()
    if block_rows is None:
        # ~4 blocks per worker bounds scheduling skew without tiny blocks
        block_rows = max(4096, -(-n // max(nthreads * 4, 1)))
    starts = list(range(0, n, block_rows)) or [0]

    def _blk(s):
        return tokenize_block(lines[s:s + block_rows], sep, ncol)

    mats = _run(_blk, starts, nthreads)
    mat = mats[0] if len(mats) == 1 else np.concatenate(mats, axis=0)
    info = dict(n_chunks=len(starts), n_threads=min(nthreads, len(starts)),
                native=False)
    return [mat[:, c] for c in range(ncol)], info
