"""Vec — one column of a distributed Frame.

Reference parity: `h2o-core/src/main/java/water/fvec/Vec.java` and the ~20
compressed `Chunk` encodings (`C0DChunk`…`CXIChunk`). The reference keeps a
Vec as a homed array of per-node compressed chunks read through
`Chunk.atd(row)`; on TPU a Vec is a single dense `jax.Array` whose leading
axis is (optionally) sharded over the ``hosts`` mesh axis. Compression is
XLA's problem (bf16/int8 casts at op boundaries), not the storage layer's —
dense HBM arrays feed the MXU; chunk decompression per element would not.

Type system (mirrors `Vec.get_type_str()`): ``real``, ``int``, ``enum``
(categorical with a string domain), ``time``, ``string``. NA encodings:
NaN for real/int (stored f32/f64), -1 for enum codes, None in string pool.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

TYPES = ("real", "int", "enum", "time", "string")

# numpy ≥2.0 ships ufunc-backed string ops that run at C speed and release
# the GIL; np.char is the semantically identical slow fallback
_S = np.strings if hasattr(np, "strings") else np.char


def _certified_str(arr: np.ndarray, assume_str: bool) -> bool:
    """May the vectorized string kernels touch this array? ``U`` always;
    ``S`` only under the tokenizer's `assume_str` certificate (its fast
    path is ASCII-gated, so bytes⇄str round-trips are lossless); object
    arrays when certified or verified all-`str` — any other element type
    (floats, None, np.str_, user bytes) keeps the exact per-element loop
    semantics. The single source of truth for every coercer's fast/slow
    dispatch, so NA/strip/intern parity can't drift between them."""
    kind = arr.dtype.kind
    if kind == "U":
        return True
    if kind == "S":
        return assume_str
    if kind == "O":
        return assume_str or all(type(v) is str for v in arr.tolist())
    return False


def bulk_try_numeric(col, na_tokens, strip_tokens: bool = False,
                     assume_str: bool = False) -> np.ndarray:
    """Vectorized `[nan if v in na_tokens else float(v) for v in col]` —
    one unicode cast + `np.isin` NA mask + a single bulk str→float64 cast
    (all of which numpy runs without the GIL) instead of a per-element
    `float()` loop. Raises TypeError/ValueError exactly when the
    per-element loop would, so callers' numeric-vs-categorical try/except
    decisions are unchanged.

    `strip_tokens` applies the parser's wider NA rule
    (`str(v).strip() in na_tokens`). `assume_str` (set by the tokenizer
    paths, whose columns are str by construction) skips the element-type
    scan; without it, columns holding any non-str element (python dicts
    can carry floats/None) drop to the exact per-element loop —
    `float(np.float32(0.1))` and `float("0.1")` differ in the last bits,
    and bit-identity with the historical path wins over speed there."""
    arr = np.asarray(col)
    n = arr.shape[0]
    if n == 0:
        return np.empty(0, np.float64)
    if not _certified_str(arr, assume_str):
        # non-str objects and bytes columns: the loop IS the semantics
        if strip_tokens:
            return np.asarray(
                [np.nan if str(v).strip() in na_tokens else float(v)
                 for v in arr], dtype=np.float64)
        return np.asarray(
            [np.nan if v in na_tokens else float(v) for v in arr],
            dtype=np.float64)
    u = arr.astype("U") if arr.dtype.kind == "O" else arr
    if u.dtype.kind == "S":
        na = [t.encode() for t in na_tokens if isinstance(t, str)]
    else:
        na = [t for t in na_tokens if isinstance(t, str)]
    key = _S.strip(u) if strip_tokens else u
    mask = np.isin(key, na)
    out = np.full(n, np.nan, np.float64)
    vals = u[~mask]
    if vals.size:
        try:
            conv = vals.astype(np.float64)
        except (TypeError, ValueError):
            # numpy's parser rejects a few forms float() accepts ("1_0",
            # non-ASCII digits); the loop is the semantics of record — and
            # it raises to the caller exactly like the historical path
            conv = np.asarray(
                [float(v.decode() if isinstance(v, bytes) else v)
                 for v in vals], dtype=np.float64)
        out[~mask] = conv
    return out


def _intern_enum(col: np.ndarray, na_tokens=("", "NA", "na", None),
                 assume_str: bool = False) -> Vec:
    """Categorical intern (`water/parser/Categorical.java`): NA-mask, then
    sorted uniques as the domain and positions as codes. Pure-str columns
    take a unicode-array route (`np.unique` over fixed-width unicode is a
    C sort; over object arrays it is a python-compare sort) — unicode
    code-point order equals python str ordering, so domains and codes are
    bit-identical either way."""
    arr = np.asarray(col)
    if _certified_str(arr, assume_str):
        u = arr.astype("U") if arr.dtype.kind == "O" else arr
        if u.dtype.kind == "S":
            # tokenizer bytes column (ASCII-gated): byte order equals
            # code-point order, so the sorted domain is identical
            na = [t.encode() for t in na_tokens if isinstance(t, str)]
        else:
            na = [t for t in na_tokens if isinstance(t, str)]
        mask = np.isin(u, na)
        domain, codes = np.unique(u[~mask], return_inverse=True)
        labels = ([d.decode() for d in domain] if u.dtype.kind == "S"
                  else [str(d) for d in domain])
    else:
        mask = np.asarray([v in na_tokens for v in arr])
        domain, codes = np.unique(np.asarray(arr)[~mask],
                                  return_inverse=True)
        labels = [str(d) for d in domain]
    full = np.full(len(arr), -1, dtype=np.int32)
    full[~mask] = codes.astype(np.int32)
    return Vec(full, "enum", domain=labels)


class Vec:
    __slots__ = ("data", "type", "domain", "_strings")

    def __init__(
        self,
        data,
        type: str = "real",
        domain: Optional[List[str]] = None,
        strings: Optional[np.ndarray] = None,
    ):
        if type not in TYPES:
            raise ValueError(f"bad vec type {type!r}")
        self.type = type
        self.domain = list(domain) if domain is not None else None
        self._strings = strings  # host-side object array for type == "string"
        if type == "string":
            self.data = None
        else:
            # columns are HOST-resident numpy; device placement (HBM, row-
            # sharded) happens once per training run inside the algorithms —
            # eager per-column device_put would round-trip the axon tunnel
            # on every munging op
            arr = np.asarray(data)
            if type == "enum":
                arr = arr.astype(np.int32)
            elif arr.dtype not in (np.float32, np.float64):
                arr = arr.astype(np.float32)
            self.data = arr

    # -- construction -------------------------------------------------------
    @staticmethod
    def from_numpy(col: np.ndarray, type_hint: Optional[str] = None,
                   assume_str: bool = False) -> "Vec":
        """Build a Vec from a host column, inferring type like
        `water/parser/ParseSetup.java` column-type guessing. `assume_str`
        certifies every element is a python str (the tokenizer paths),
        skipping the per-element type scans of the vectorized coercers."""
        if col.dtype.kind in "OUS":
            work = col
            if col.dtype.kind == "O" and _certified_str(col, assume_str):
                # one unicode cast shared by the numeric try AND the intern
                # (each would otherwise pay its own object→U conversion)
                work = col.astype("U")
            if type_hint == "enum":
                return _intern_enum(work, assume_str=assume_str)
            # try numeric, else categorical intern (water/parser/Categorical.java)
            try:
                as_num = bulk_try_numeric(work, ("", "NA", "na", "nan", None),
                                          assume_str=assume_str)
                return Vec(_maybe_f32(as_num),
                           "real" if not _all_int(as_num) else "int")
            except (TypeError, ValueError):
                pass
            if type_hint == "string":
                return Vec(None, "string", strings=np.asarray(col, dtype=object))
            return _intern_enum(work, assume_str=assume_str)
        col = np.asarray(col)
        if type_hint == "time":
            return Vec(col.astype(np.float64), "time")
        if type_hint == "enum":
            valid = ~np.isnan(col.astype(np.float64))
            domain, codes = np.unique(col[valid], return_inverse=True)
            full = np.full(len(col), -1, dtype=np.int32)
            full[valid] = codes.astype(np.int32)
            # integral numeric levels print without the ".0" (h2o's asfactor)
            labels = [
                str(int(d)) if float(d) == int(d) else str(d) for d in domain
            ]
            return Vec(full, "enum", domain=labels)
        t = "int" if col.dtype.kind in "iub" or _all_int(col) else "real"
        return Vec(_maybe_f32(col.astype(np.float64)), t)

    # -- properties ---------------------------------------------------------
    def __len__(self) -> int:
        return len(self._strings) if self.type == "string" else int(self.data.shape[0])

    @property
    def nlevels(self) -> int:
        return len(self.domain) if self.domain else 0

    def isna_np(self) -> np.ndarray:
        if self.type == "string":
            return np.asarray([s is None for s in self._strings])
        a = np.asarray(self.data)
        return (a < 0) if self.type == "enum" else np.isnan(a)

    def to_numpy(self) -> np.ndarray:
        if self.type == "string":
            return self._strings
        return np.asarray(self.data)

    def numeric_np(self) -> np.ndarray:
        """Column as float64 with NaN NAs (enum -> code as float)."""
        a = np.asarray(self.data, dtype=np.float64)
        if self.type == "enum":
            a = np.where(a < 0, np.nan, a)
        return a

    # -- stats (the rollups of water/fvec/RollupStats.java) ------------------
    def mean(self) -> float:
        return float(np.nanmean(self.numeric_np()))

    def sd(self) -> float:
        return float(np.nanstd(self.numeric_np(), ddof=1))

    def min(self) -> float:
        return float(np.nanmin(self.numeric_np()))

    def max(self) -> float:
        return float(np.nanmax(self.numeric_np()))

    def nacnt(self) -> int:
        return int(self.isna_np().sum())

    def take(self, idx: np.ndarray) -> "Vec":
        if self.type == "string":
            return Vec(None, "string", strings=self._strings[idx])
        return Vec(np.asarray(self.data)[idx], self.type, domain=self.domain)

    def __repr__(self):
        return f"Vec(type={self.type}, len={len(self)}, domain={self.nlevels or None})"


def _maybe_f32(col: np.ndarray) -> np.ndarray:
    """Downcast f64 → f32 unless magnitudes exceed f32's exact-integer
    range — epoch-ms timestamps ("time" columns) would lose minutes."""
    fin = col[np.isfinite(col)]
    big = float(np.abs(fin).max()) if fin.size else 0.0
    return col if big > (1 << 24) else col.astype(np.float32)


def _all_int(a: np.ndarray) -> bool:
    with np.errstate(invalid="ignore"):
        fin = a[np.isfinite(a)]
        return fin.size > 0 and bool(np.all(fin == np.round(fin)))
