"""Vec — one column of a distributed Frame.

Reference parity: `h2o-core/src/main/java/water/fvec/Vec.java` and the ~20
compressed `Chunk` encodings (`C0DChunk`…`CXIChunk`). The reference keeps a
Vec as a homed array of per-node compressed chunks read through
`Chunk.atd(row)`; on TPU a Vec is a single dense `jax.Array` whose leading
axis is (optionally) sharded over the ``hosts`` mesh axis. Compression is
XLA's problem (bf16/int8 casts at op boundaries), not the storage layer's —
dense HBM arrays feed the MXU; chunk decompression per element would not.

Type system (mirrors `Vec.get_type_str()`): ``real``, ``int``, ``enum``
(categorical with a string domain), ``time``, ``string``. NA encodings:
NaN for real/int (stored f32/f64), -1 for enum codes, None in string pool.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

TYPES = ("real", "int", "enum", "time", "string")


class Vec:
    __slots__ = ("data", "type", "domain", "_strings")

    def __init__(
        self,
        data,
        type: str = "real",
        domain: Optional[List[str]] = None,
        strings: Optional[np.ndarray] = None,
    ):
        if type not in TYPES:
            raise ValueError(f"bad vec type {type!r}")
        self.type = type
        self.domain = list(domain) if domain is not None else None
        self._strings = strings  # host-side object array for type == "string"
        if type == "string":
            self.data = None
        else:
            # columns are HOST-resident numpy; device placement (HBM, row-
            # sharded) happens once per training run inside the algorithms —
            # eager per-column device_put would round-trip the axon tunnel
            # on every munging op
            arr = np.asarray(data)
            if type == "enum":
                arr = arr.astype(np.int32)
            elif arr.dtype not in (np.float32, np.float64):
                arr = arr.astype(np.float32)
            self.data = arr

    # -- construction -------------------------------------------------------
    @staticmethod
    def from_numpy(col: np.ndarray, type_hint: Optional[str] = None) -> "Vec":
        """Build a Vec from a host column, inferring type like
        `water/parser/ParseSetup.java` column-type guessing."""
        if col.dtype.kind in "OUS":
            if type_hint == "enum":
                mask = np.asarray([v in ("", "NA", "na", None) for v in col])
                domain, codes = np.unique(np.asarray(col)[~mask], return_inverse=True)
                full = np.full(len(col), -1, dtype=np.int32)
                full[~mask] = codes.astype(np.int32)
                return Vec(full, "enum", domain=[str(d) for d in domain])
            # try numeric, else categorical intern (water/parser/Categorical.java)
            try:
                as_num = np.asarray(
                    [np.nan if v in ("", "NA", "na", "nan", None) else float(v) for v in col],
                    dtype=np.float64,
                )
                return Vec(_maybe_f32(as_num),
                           "real" if not _all_int(as_num) else "int")
            except (TypeError, ValueError):
                pass
            if type_hint == "string":
                return Vec(None, "string", strings=np.asarray(col, dtype=object))
            mask = np.asarray([v in ("", "NA", "na", None) for v in col])
            domain, codes = np.unique(np.asarray(col)[~mask], return_inverse=True)
            full = np.full(len(col), -1, dtype=np.int32)
            full[~mask] = codes.astype(np.int32)
            return Vec(full, "enum", domain=[str(d) for d in domain])
        col = np.asarray(col)
        if type_hint == "time":
            return Vec(col.astype(np.float64), "time")
        if type_hint == "enum":
            valid = ~np.isnan(col.astype(np.float64))
            domain, codes = np.unique(col[valid], return_inverse=True)
            full = np.full(len(col), -1, dtype=np.int32)
            full[valid] = codes.astype(np.int32)
            # integral numeric levels print without the ".0" (h2o's asfactor)
            labels = [
                str(int(d)) if float(d) == int(d) else str(d) for d in domain
            ]
            return Vec(full, "enum", domain=labels)
        t = "int" if col.dtype.kind in "iub" or _all_int(col) else "real"
        return Vec(_maybe_f32(col.astype(np.float64)), t)

    # -- properties ---------------------------------------------------------
    def __len__(self) -> int:
        return len(self._strings) if self.type == "string" else int(self.data.shape[0])

    @property
    def nlevels(self) -> int:
        return len(self.domain) if self.domain else 0

    def isna_np(self) -> np.ndarray:
        if self.type == "string":
            return np.asarray([s is None for s in self._strings])
        a = np.asarray(self.data)
        return (a < 0) if self.type == "enum" else np.isnan(a)

    def to_numpy(self) -> np.ndarray:
        if self.type == "string":
            return self._strings
        return np.asarray(self.data)

    def numeric_np(self) -> np.ndarray:
        """Column as float64 with NaN NAs (enum -> code as float)."""
        a = np.asarray(self.data, dtype=np.float64)
        if self.type == "enum":
            a = np.where(a < 0, np.nan, a)
        return a

    # -- stats (the rollups of water/fvec/RollupStats.java) ------------------
    def mean(self) -> float:
        return float(np.nanmean(self.numeric_np()))

    def sd(self) -> float:
        return float(np.nanstd(self.numeric_np(), ddof=1))

    def min(self) -> float:
        return float(np.nanmin(self.numeric_np()))

    def max(self) -> float:
        return float(np.nanmax(self.numeric_np()))

    def nacnt(self) -> int:
        return int(self.isna_np().sum())

    def take(self, idx: np.ndarray) -> "Vec":
        if self.type == "string":
            return Vec(None, "string", strings=self._strings[idx])
        return Vec(np.asarray(self.data)[idx], self.type, domain=self.domain)

    def __repr__(self):
        return f"Vec(type={self.type}, len={len(self)}, domain={self.nlevels or None})"


def _maybe_f32(col: np.ndarray) -> np.ndarray:
    """Downcast f64 → f32 unless magnitudes exceed f32's exact-integer
    range — epoch-ms timestamps ("time" columns) would lose minutes."""
    fin = col[np.isfinite(col)]
    big = float(np.abs(fin).max()) if fin.size else 0.0
    return col if big > (1 << 24) else col.astype(np.float32)


def _all_int(a: np.ndarray) -> bool:
    with np.errstate(invalid="ignore"):
        fin = a[np.isfinite(a)]
        return fin.size > 0 and bool(np.all(fin == np.round(fin)))
