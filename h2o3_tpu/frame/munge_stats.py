"""Munge observability — per-op stage timings + throughput counters.

The vectorized munging engine (frame/rapids.py merge/group-by/pivot/table,
frame/frame.py apply-over-rows, the rapids_expr time/string prims) records
one entry per completed op: input/output rows, wall seconds and the
per-stage split (e.g. merge's factorize / combine / match / assemble — the
stages of `AstMerge`'s radix join, `water/rapids/ast/prims/mungers/
AstMerge.java`). Readers:

- `GET /3/Munge/metrics` and the `munge` section of `/3/Profiler`
  (via runtime/profiler.munge_stats) serve `snapshot()`;
- `runtime/phases.py` receives the same marks under ``munge_<op>`` keys,
  so bench.py's phase decomposition covers munging next to ingest and
  h2d/compile/compute.

`path` tags how the op executed: "vectorized" (the columnar kernels),
"fallback" (a vectorized attempt that dropped to the exact per-row loop —
e.g. a row callable that doesn't vectorize), or "legacy" (the seed path,
forced by ``H2O3_MUNGE_LEGACY=1``).
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from typing import Dict, Optional

_LOCK = threading.Lock()
_TOTALS = dict(ops=0, rows_in=0, rows_out=0, secs=0.0)
_PER_OP: Dict[str, Dict] = {}
_LAST: Dict = {}


_REGISTRY = None


def _registry():
    """Central-registry counters backing the /3/Munge/metrics totals
    (scraped at GET /3/Metrics; per-op detail labeled by op/path).
    Memoized — this runs on every munge op."""
    global _REGISTRY
    if _REGISTRY is not None:
        return _REGISTRY
    from ..runtime import metrics_registry as reg

    c = {
        "ops": reg.counter("h2o3_munge_ops", "completed munge ops",
                           labelnames=("op", "path")),
        "errors": reg.counter("h2o3_munge_errors", "munge ops that raised",
                              labelnames=("op",)),
        "rows_in": reg.counter("h2o3_munge_rows_in", "input rows munged"),
        "rows_out": reg.counter("h2o3_munge_rows_out",
                                "output rows produced"),
        "secs": reg.counter("h2o3_munge_seconds",
                            "wall seconds spent in munge ops"),
    }
    for field, metric in (("totals.ops", "h2o3_munge_ops"),
                          ("totals.rows_in", "h2o3_munge_rows_in"),
                          ("totals.rows_out", "h2o3_munge_rows_out"),
                          ("totals.secs", "h2o3_munge_seconds")):
        reg.bind_rest_field("munge", field, metric)
    _REGISTRY = c
    return c


def legacy_enabled() -> bool:
    """True when ``H2O3_MUNGE_LEGACY=1`` forces the seed per-row paths
    (the bit-exact comparator the parity tests diff against)."""
    return os.environ.get("H2O3_MUNGE_LEGACY", "").lower() in (
        "1", "true", "yes")


@contextmanager
def stage(marks: Dict[str, float], name: str):
    """Accumulate wall-clock of one munge stage into `marks[name]`."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        marks[name] = marks.get(name, 0.0) + (time.perf_counter() - t0)


def record(op: str, rows_in: int, rows_out: int, secs: float,
           stages: Optional[Dict[str, float]] = None,
           path: str = "vectorized", error: bool = False) -> None:
    """Book one finished munge op into the cumulative totals + per-op
    counters + `last`, and forward the wall-clock to runtime/phases as
    ``munge_<op>``. Ops that RAISED book with ``error=True`` and
    rows_out=0 — a failed call must not fabricate throughput."""
    from ..runtime import phases as _phz

    _phz.add(f"munge_{op}", secs)
    secs = max(secs, 1e-9)
    entry = dict(
        op=op, rows_in=int(rows_in), rows_out=int(rows_out),
        secs=round(secs, 6),
        rows_per_s=round(rows_in / secs, 1),
        path=path,
        stages={k: round(v, 6) for k, v in (stages or {}).items()},
    )
    if error:
        entry["error"] = True
    reg = _registry()
    reg["ops"].inc(1, op, path)
    if error:
        reg["errors"].inc(1, op)
    reg["rows_in"].inc(int(rows_in))
    reg["rows_out"].inc(int(rows_out))
    reg["secs"].inc(secs)
    from ..runtime import tracing as _tracing

    _tracing.record_span(f"munge:{op}", secs, kind="munge",
                         rows_in=int(rows_in), rows_out=int(rows_out),
                         path=path, **(dict(error=True) if error else {}))
    with _LOCK:
        _TOTALS["ops"] += 1
        _TOTALS["rows_in"] += int(rows_in)
        _TOTALS["rows_out"] += int(rows_out)
        _TOTALS["secs"] += secs
        po = _PER_OP.setdefault(op, dict(calls=0, errors=0, rows_in=0,
                                         rows_out=0, secs=0.0, paths={}))
        po["calls"] += 1
        if error:
            po["errors"] += 1
        po["rows_in"] += int(rows_in)
        po["rows_out"] += int(rows_out)
        po["secs"] += secs
        po["paths"][path] = po["paths"].get(path, 0) + 1
        _LAST.clear()
        _LAST.update(entry)


@contextmanager
def op(name: str, rows_in: int, stages: Optional[Dict[str, float]] = None,
       path: str = "vectorized"):
    """Time one munge op; the caller sets ``out['rows_out']`` (defaults to
    rows_in) and may retag ``out['path']`` before the block exits. An op
    that raises books rows_out=0 with ``error=True``."""
    out = dict(rows_out=rows_in, path=path)
    t0 = time.perf_counter()
    try:
        yield out
    except BaseException:
        record(name, rows_in, 0, time.perf_counter() - t0, stages=stages,
               path=out.get("path", path), error=True)
        raise
    record(name, rows_in, out.get("rows_out", rows_in),
           time.perf_counter() - t0, stages=stages,
           path=out.get("path", path))


def snapshot() -> Dict:
    """Cumulative + per-op + last-op counters (the /3/Munge/metrics body)."""
    with _LOCK:
        totals = dict(_TOTALS)
        per_op = {k: dict(v, paths=dict(v["paths"]))
                  for k, v in _PER_OP.items()}
        last: Optional[Dict] = dict(_LAST) if _LAST else None
    secs = max(totals["secs"], 1e-9)
    for v in per_op.values():
        v["secs"] = round(v["secs"], 6)
        v["rows_per_s"] = round(v["rows_in"] / max(v["secs"], 1e-9), 1)
    return dict(
        totals=dict(
            ops=totals["ops"], rows_in=totals["rows_in"],
            rows_out=totals["rows_out"], secs=round(totals["secs"], 6),
            rows_per_s=round(totals["rows_in"] / secs, 1),
        ),
        ops=per_op,
        last=last,
    )


def reset() -> None:
    with _LOCK:
        _TOTALS.update(ops=0, rows_in=0, rows_out=0, secs=0.0)
        _PER_OP.clear()
        _LAST.clear()
