"""Feature binning — quantize numeric columns to small-int bin codes.

Reference parity: `h2o-algos/src/main/java/hex/tree/DHistogram.java` —
`histogram_type` ∈ {UniformAdaptive, Random, QuantilesGlobal} and
`hex/quantile/Quantile.java` (exact distributed quantiles feeding
QuantilesGlobal). The reference recomputes per-node bin ranges every tree
level; on TPU we pre-quantize the whole matrix once per model into static
int codes (the `gpu_hist`/LightGBM design) so every histogram pass is a
fixed-shape integer op that XLA can tile — per-level re-binning would mean
dynamic shapes and host round-trips.

Encoding: codes in [0, nbins-2] for values, NA → reserved last bin
(nbins-1); split semantics `code <= split_bin` ⇒ NAs traverse right, and
the split search may place the threshold so that NA-right is the best gain
(H2O sends NAs to whichever side the gain prefers via its NA bucket —
DHistogram's `_vals` NA slot).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

HISTOGRAM_TYPES = ("UniformAdaptive", "QuantilesGlobal", "Random", "AUTO")


@dataclass
class BinnedMatrix:
    """Static pre-quantized design matrix for tree algos."""

    codes: np.ndarray        # (nrow, nfeat) uint8/uint16 bin codes
    edges: List[np.ndarray]  # per-feature right bin edges (len nbins-2)
    nbins: int               # includes the NA bin
    names: List[str]
    is_categorical: np.ndarray  # (nfeat,) bool
    domains: List[Optional[List[str]]]

    @property
    def na_bin(self) -> int:
        return self.nbins - 1

    def bin_value(self, feat: int, b: int) -> float:
        """Representative split value for MOJO export (midpoint semantics of
        DTree.Split._splat)."""
        e = self.edges[feat]
        if len(e) == 0:
            return 0.0
        b = min(b, len(e) - 1)
        return float(e[b])


def build_bins(
    X: np.ndarray,
    nbins: int = 256,
    histogram_type: str = "UniformAdaptive",
    names: Optional[Sequence[str]] = None,
    is_categorical: Optional[np.ndarray] = None,
    domains: Optional[List[Optional[List[str]]]] = None,
    seed: int = 0,
    col_ranges: Optional[np.ndarray] = None,
    col_quantile_edges: Optional[List[Optional[np.ndarray]]] = None,
) -> BinnedMatrix:
    """Quantize columns of X (float, NaN=NA) into bin codes.

    nbins counts value bins + 1 NA bin. Categorical columns use the identity
    binning (code = category id) like DHistogram's categorical path where
    each level is its own bin (clamped at nbins-2).
    """
    if histogram_type not in HISTOGRAM_TYPES:
        raise ValueError(f"histogram_type {histogram_type!r} not in {HISTOGRAM_TYPES}")
    if histogram_type == "AUTO":
        histogram_type = "UniformAdaptive"
    X = np.asarray(X, dtype=np.float64)
    n, f = X.shape
    nvalue = nbins - 1
    names = list(names) if names else [f"C{i+1}" for i in range(f)]
    is_categorical = (
        np.asarray(is_categorical, dtype=bool)
        if is_categorical is not None
        else np.zeros(f, dtype=bool)
    )
    domains = domains if domains is not None else [None] * f
    rng = np.random.default_rng(seed)

    dtype = np.uint8 if nbins <= 256 else np.uint16
    codes = np.empty((n, f), dtype=dtype)
    edges: List[np.ndarray] = []
    for j in range(f):
        col = X[:, j]
        na = np.isnan(col)
        if is_categorical[j]:
            c = np.clip(np.nan_to_num(col, nan=0).astype(np.int64), 0, nvalue - 1)
            e = np.arange(0.5, nvalue - 0.5, 1.0)  # identity edges for export
        else:
            fin = col[~na]
            if fin.size == 0 and col_ranges is None:
                e = np.zeros(0)
                c = np.zeros(n, dtype=np.int64)
            else:
                # col_ranges: externally supplied global (lo, hi) — a
                # multi-host cloud's min/max collective, so every process
                # builds IDENTICAL edges from its local shard
                if col_ranges is not None:
                    lo, hi = float(col_ranges[j, 0]), float(col_ranges[j, 1])
                    if not np.isfinite(lo):
                        e = np.zeros(0)
                        c = np.zeros(n, dtype=np.int64)
                        codes[:, j] = np.where(na, nvalue, c).astype(dtype)
                        edges.append(e)
                        continue
                else:
                    lo, hi = float(fin.min()), float(fin.max())
                if histogram_type == "UniformAdaptive":
                    e = np.linspace(lo, hi, nvalue + 1)[1:-1]
                    # arithmetic quantize == searchsorted(e, col, 'left') for
                    # uniform edges, ~30x cheaper than the binary search
                    step = (hi - lo) / nvalue if hi > lo else 1.0
                    c = np.ceil(np.nan_to_num((col - lo) / step, nan=0.0)
                                ).astype(np.int64) - 1
                    c = np.where(na, 0, np.clip(c, 0, nvalue - 1))
                    codes[:, j] = np.where(na, nvalue, c).astype(dtype)
                    edges.append(np.asarray(e, dtype=np.float64))
                    continue
                elif histogram_type == "QuantilesGlobal":
                    if (col_quantile_edges is not None
                            and col_quantile_edges[j] is not None):
                        # externally supplied GLOBAL quantile edges — a
                        # multi-host cloud's distributed refinement, so
                        # every process bins with identical cut points
                        e = np.asarray(col_quantile_edges[j], np.float64)
                    else:
                        qs = np.linspace(0, 1, nvalue + 1)[1:-1]
                        e = np.unique(np.quantile(fin, qs))
                else:  # Random (DHistogram histogram_type=Random)
                    if hi > lo:
                        e = np.sort(rng.uniform(lo, hi, nvalue - 1))
                    else:
                        e = np.zeros(0)
                c = np.searchsorted(e, col, side="left")
                c = np.nan_to_num(c, nan=0).astype(np.int64)
        c = np.where(na, nvalue, np.clip(c, 0, nvalue - 1))
        codes[:, j] = c.astype(dtype)
        edges.append(np.asarray(e, dtype=np.float64))
    return BinnedMatrix(
        codes=codes, edges=edges, nbins=nbins, names=names,
        is_categorical=is_categorical, domains=list(domains),
    )


def bin_apply(bm: BinnedMatrix, X: np.ndarray) -> np.ndarray:
    """Quantize new data with the training-time edges (scoring path uses raw
    values via the exported thresholds instead; this is for OOB/valid reuse)."""
    X = np.asarray(X, dtype=np.float64)
    n, f = X.shape
    out = np.empty((n, f), dtype=bm.codes.dtype)
    nvalue = bm.nbins - 1
    for j in range(f):
        col = X[:, j]
        na = np.isnan(col)
        if bm.is_categorical[j]:
            c = np.clip(np.nan_to_num(col, nan=0).astype(np.int64), 0, nvalue - 1)
        else:
            c = np.searchsorted(bm.edges[j], col, side="left")
            c = np.clip(np.nan_to_num(c, nan=0).astype(np.int64), 0, nvalue - 1)
        out[:, j] = np.where(na, nvalue, c).astype(bm.codes.dtype)
    return out
