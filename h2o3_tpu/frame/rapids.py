"""Rapids subset — dataframe munging ops.

Reference parity: `h2o-core/src/main/java/water/rapids/` — the Lisp-AST
interpreter (`Rapids.java`, `Session.java`) and its ~100 `ast/prims/**` ops;
the ones replicated here are the workhorses the reference's own tests lean
on: `AstGroup` (group-by aggregates), `AstMerge` (radix join),
`AstDdply`-style application, quantiles, value counts, ifelse/apply basics.

The client-server indirection is collapsed (no Lisp strings, no /99/Rapids
POST): ops execute eagerly as numpy reductions — at frame-munging scale the
host is the right place; device time is reserved for training loops.
GroupBy mirrors `h2o-py/h2o/group_by.py`'s builder surface
(`fr.group_by(...).sum().mean().get_frame()`).

Since the vectorized-munging round, the hot ops run as columnar kernels:
`merge` is a factorized radix join (per-key-column code factorization,
mixed-radix combine, one stable sort + searchsorted match producing gather
indices — zero per-row python objects), `pivot`/`table` are
factorize+scatter. ``H2O3_MUNGE_LEGACY=1`` re-engages the seed per-row
paths as a bit-exact comparator (see docs/munging.md); every op books its
stage timings into `frame/munge_stats.py`.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from . import munge_stats
from .frame import Frame
from .vec import Vec

_AGGS = ("count", "sum", "mean", "min", "max", "sd", "var", "median", "mode", "first", "last")
_NA_MODES = ("all", "ignore", "rm")


class GroupBy:
    """`h2o-py/h2o/group_by.py` builder over `AstGroup` semantics.

    NA handling per aggregate (`AstGroup.NAHandling`): ``"all"`` propagates
    NA into the aggregate (a group containing an NA aggregates to NA),
    ``"rm"`` removes NA rows from both the numerator and the denominator,
    ``"ignore"`` skips NAs in the accumulation but keeps the rows in the
    denominator (mean/var/sd divide by the FULL group size)."""

    def __init__(self, frame: Frame, by: Union[str, Sequence[str]]):
        self.frame = frame
        self.by = [by] if isinstance(by, str) else list(by)
        self._aggs: List = []  # (op, col, na)

    @staticmethod
    def _check_na(na):
        if na not in _NA_MODES:
            raise ValueError(
                f"group_by: na must be one of {_NA_MODES}, got {na!r}")
        return na

    def _add(self, op, col, na):
        self._check_na(na)
        cols = col if isinstance(col, (list, tuple)) else [col]
        for c in cols:
            self._aggs.append((op, c, na))
        return self

    def count(self, na="all"):
        self._check_na(na)
        self._aggs.append(("count", None, na))
        return self

    def sum(self, col=None, na="all"):
        return self._add("sum", col or self._numeric_cols(), na)

    def mean(self, col=None, na="all"):
        return self._add("mean", col or self._numeric_cols(), na)

    def min(self, col=None, na="all"):
        return self._add("min", col or self._numeric_cols(), na)

    def max(self, col=None, na="all"):
        return self._add("max", col or self._numeric_cols(), na)

    def sd(self, col=None, na="all"):
        return self._add("sd", col or self._numeric_cols(), na)

    def var(self, col=None, na="all"):
        return self._add("var", col or self._numeric_cols(), na)

    def median(self, col=None, na="all"):
        return self._add("median", col or self._numeric_cols(), na)

    def mode(self, col=None, na="all"):
        return self._add("mode", col or self._numeric_cols(), na)

    def _numeric_cols(self):
        return [n for n in self.frame.names
                if n not in self.by and self.frame.vec(n).type in ("real", "int")]

    def get_frame(self) -> Frame:
        with munge_stats.op("group_by", self.frame.nrow) as _rec:
            out = self._get_frame()
            _rec["rows_out"] = out.nrow
        return out

    def _get_frame(self) -> Frame:
        fr = self.frame
        keys = [fr.vec(b) for b in self.by]
        key_codes = []
        key_domains = []
        for v in keys:
            if v.type == "enum":
                codes = np.asarray(v.data, np.int64)
                dom = list(v.domain or [])
                # NA keys (code -1) are their OWN group — fed raw into the
                # mixed radix, -1 used to decode as the LAST domain label
                # and silently collide with that group
                key_codes.append(np.where(codes >= 0, codes, len(dom)))
                key_domains.append(np.asarray(dom + [None], dtype=object))
            else:
                col = v.numeric_np()
                uniq, inv = np.unique(col, return_inverse=True)
                key_codes.append(inv.astype(np.int64))
                key_domains.append(uniq)
        combined = key_codes[0].copy().astype(np.int64)
        sizes = [len(d) for d in key_domains]
        size = max(sizes[0], 1)
        for i in range(1, len(key_codes)):
            if size * max(sizes[i], 1) >= (1 << 62):
                # compact before the radix product could overflow int64
                # (same guard as the merge radix) — decode below goes via
                # first-occurrence rows, so compaction is free
                u, combined = np.unique(combined, return_inverse=True)
                combined = combined.astype(np.int64)
                size = len(u)
            combined = combined * max(sizes[i], 1) + key_codes[i]
            size *= max(sizes[i], 1)
        groups, first_idx, ginv = np.unique(
            combined, return_index=True, return_inverse=True)
        G = len(groups)

        out: Dict[str, np.ndarray] = {}
        sort_keys: Dict[str, np.ndarray] = {}
        for i, b in enumerate(self.by):
            # decode each group's key from its FIRST member row — immune
            # to whatever compaction the combine step did
            idx = np.asarray(key_codes[i], np.int64)[first_idx]
            dom = key_domains[i]
            vals = dom[idx]
            out[b] = vals
            if dom.dtype == object:
                # label-sorted positions, NA (None) last — None isn't
                # comparable to str, so the lexsort runs on positions;
                # remap is O(|domain|), the gather O(G) in C
                labels = [d for d in dom if d is not None]
                pos = {d: p for p, d in enumerate(sorted(labels))}
                remap = np.asarray(
                    [pos.get(d, len(labels)) for d in dom], np.int64)
                sort_keys[b] = remap[idx]
            else:
                sort_keys[b] = vals  # numeric: value order, NaN sorts last
        order = np.lexsort([sort_keys[b] for b in reversed(self.by)])

        # vectorized per-group reductions: moments via bincount-with-weights,
        # order statistics via one sort + reduceat — O(n log n), never O(G·n)
        sort_cache: Dict[str, tuple] = {}

        def _sorted(colname, c):
            if colname not in sort_cache:
                valid = ~np.isnan(c)
                gv = ginv[valid]
                cv = c[valid]
                order = np.lexsort((cv, gv))
                gs, cs = gv[order], cv[order]
                starts = np.flatnonzero(np.r_[True, gs[1:] != gs[:-1]])
                sort_cache[colname] = (gs, cs, starts)
            return sort_cache[colname]

        cnt_all = np.bincount(ginv, minlength=G).astype(np.float64)
        for op, col, na in self._aggs:
            if op == "count":
                # nrow with a referenced column honors na="rm" (count the
                # non-NA rows, AstGroup's nrow agg); the builder's bare
                # count() has no column, so it is always the group size
                if col is not None and na == "rm":
                    # isna_np covers every vec type (string columns have
                    # no numeric view — numeric_np would crash)
                    valid = ~fr.vec(col).isna_np()
                    out["nrow"] = np.bincount(
                        ginv[valid], minlength=G).astype(np.float64)
                else:
                    out["nrow"] = cnt_all.copy()
                continue
            c = fr.vec(col).numeric_np()
            name = f"{op}_{col}"
            agg = np.full(G, np.nan)
            isna = np.isnan(c)
            valid = ~isna
            gv = ginv[valid]
            cv = c[valid]
            cnt = np.bincount(gv, minlength=G).astype(np.float64)
            nz = cnt > 0
            if op in ("sum", "mean", "sd", "var"):
                s1 = np.bincount(gv, weights=cv, minlength=G)
                # "ignore": skip NAs in the accumulation but divide by the
                # FULL group size (AstGroup IGNORE keeps the rows)
                denom = cnt_all if na == "ignore" else cnt
                if op == "sum":
                    agg[nz] = s1[nz]
                elif op == "mean":
                    agg[nz] = s1[nz] / denom[nz]
                else:
                    s2 = np.bincount(gv, weights=cv * cv, minlength=G)
                    mean = np.where(nz, s1 / np.maximum(denom, 1), 0.0)
                    ss = np.maximum(s2 - denom * mean * mean, 0.0)
                    var = np.where(denom > 1, ss / np.maximum(denom - 1, 1),
                                   0.0)
                    agg[nz] = np.sqrt(var[nz]) if op == "sd" else var[nz]
            elif op in ("min", "max"):
                gs, cs, starts = _sorted(col, c)
                present = np.unique(gs)
                ends = np.r_[starts[1:], len(cs)]
                vals = cs[starts] if op == "min" else cs[ends - 1]
                agg[present] = vals
            elif op == "median":
                gs, cs, starts = _sorted(col, c)
                present = np.unique(gs)
                ends = np.r_[starts[1:], len(cs)]
                lens = ends - starts
                lo = starts + (lens - 1) // 2
                hi = starts + lens // 2
                agg[present] = 0.5 * (cs[lo] + cs[hi])
            elif op == "mode":
                # mode = longest run within (group, value)-sorted order
                gs, cs, starts = _sorted(col, c)
                runs = np.flatnonzero(
                    np.r_[True, (gs[1:] != gs[:-1]) | (cs[1:] != cs[:-1])]
                )
                run_ends = np.r_[runs[1:], len(cs)]
                run_len = run_ends - runs
                run_grp = gs[runs]
                run_val = cs[runs]
                best_order = np.lexsort((run_len, run_grp))
                gb, lb, vb = run_grp[best_order], run_len[best_order], run_val[best_order]
                last = np.flatnonzero(np.r_[gb[1:] != gb[:-1], True])
                agg[gb[last]] = vb[last]
            if na == "all" and isna.any():
                # NA propagates into the aggregate of its group
                agg[np.bincount(ginv[isna], minlength=G) > 0] = np.nan
            out[name] = agg

        return Frame.from_dict({k: np.asarray(v)[order] for k, v in out.items()})


# -- merge (AstMerge radix join) ---------------------------------------------
def _join_indices_legacy(left: Frame, right: Frame, by: Sequence[str],
                         all_x: bool, all_y: bool
                         ) -> Tuple[np.ndarray, np.ndarray]:
    """Seed hash join — per-row python tuples into a dict. Kept verbatim as
    the bit-exact comparator (``H2O3_MUNGE_LEGACY=1``)."""
    def keytuple(fr: Frame):
        cols = []
        for b in by:
            v = fr.vec(b)
            if v.type == "enum":
                dom = np.asarray(v.domain + [None], dtype=object)
                cols.append(dom[np.asarray(v.data)])
            else:
                cols.append(v.numeric_np())
        return list(zip(*[c.tolist() for c in cols])) if cols else []

    lk = keytuple(left)
    rk = keytuple(right)
    rmap: Dict = {}
    for j, k in enumerate(rk):
        rmap.setdefault(k, []).append(j)
    li, ri = [], []
    matched_r = set()
    for i, k in enumerate(lk):
        js = rmap.get(k)
        if js:
            for j in js:
                li.append(i)
                ri.append(j)
                matched_r.add(j)
        elif all_x:
            li.append(i)
            ri.append(-1)
    if all_y:
        for j in range(len(rk)):
            if j not in matched_r:
                li.append(-1)
                ri.append(j)
    return np.asarray(li, np.int64), np.asarray(ri, np.int64)


def _factorize_key_column(lv: Vec, rv: Vec, nl: int, nr: int):
    """Joint code factorization of ONE key column across both sides:
    returns (l_codes, r_codes, size, l_dead, r_dead) where equal key values
    share a code in [0, size) and ``dead`` rows can never match any row on
    the other side. Match semantics replicate the seed tuple join exactly:
    enum keys compare by LABEL (two enums with different domains still
    match; the NA level None equals None, so enum-NA matches enum-NA),
    numeric keys compare by value with NaN never equal to anything, and an
    enum column against a numeric column never matches (labels are strings,
    the tuple join compared them to floats)."""
    if lv.type == "enum" and rv.type == "enum":
        ldom = np.asarray(lv.domain or [], dtype=object)
        rdom = np.asarray(rv.domain or [], dtype=object)
        both = np.concatenate([ldom, rdom]) if (len(ldom) + len(rdom)) \
            else np.empty(0, dtype=object)
        union = np.unique(both.astype("U")) if both.size else \
            np.empty(0, dtype="U1")
        lmap = (np.searchsorted(union, ldom.astype("U")).astype(np.int64)
                if ldom.size else np.empty(0, np.int64))
        rmap = (np.searchsorted(union, rdom.astype("U")).astype(np.int64)
                if rdom.size else np.empty(0, np.int64))
        lc = np.asarray(lv.data, np.int64)
        rc = np.asarray(rv.data, np.int64)

        # the NA level (code -1 ⇒ label None) is itself matchable: None
        # equals None in the seed's tuple join — it gets code len(union)
        def _remap(codes, mapping):
            if mapping.size == 0:  # empty domain (all-NA column): every
                return np.full(codes.shape, len(union), np.int64)  # row NA
            return np.where(codes >= 0, mapping[np.maximum(codes, 0)],
                            len(union))

        l_codes = _remap(lc, lmap)
        r_codes = _remap(rc, rmap)
        return (l_codes, r_codes, len(union) + 1,
                np.zeros(nl, bool), np.zeros(nr, bool))
    if lv.type != "enum" and rv.type != "enum":
        lx = lv.numeric_np()
        rx = rv.numeric_np()
        l_dead = np.isnan(lx)
        r_dead = np.isnan(rx)
        uniq = np.unique(np.concatenate([lx[~l_dead], rx[~r_dead]]))
        l_codes = np.zeros(nl, np.int64)
        r_codes = np.zeros(nr, np.int64)
        if uniq.size:
            l_codes[~l_dead] = np.searchsorted(uniq, lx[~l_dead])
            r_codes[~r_dead] = np.searchsorted(uniq, rx[~r_dead])
        return l_codes, r_codes, max(int(uniq.size), 1), l_dead, r_dead
    # mixed enum/numeric: string labels never equal floats — no match ever
    return (np.zeros(nl, np.int64), np.zeros(nr, np.int64), 1,
            np.ones(nl, bool), np.ones(nr, bool))


def _join_indices_radix(left: Frame, right: Frame, by: Sequence[str],
                        all_x: bool, all_y: bool,
                        marks: Optional[Dict[str, float]] = None
                        ) -> Tuple[np.ndarray, np.ndarray]:
    """Factorized radix join: per-key-column joint code factorization,
    mixed-radix combine (compacting via np.unique before the radix product
    could overflow int64), then ONE stable sort of the right keys and a
    searchsorted range lookup per left key — gather indices come out of
    np.repeat/cumsum algebra with zero per-row python objects. Emits
    (li, ri) in exactly the seed hash join's row order: left rows in
    order, each left row's matches in ascending right-row order, then (for
    all_y) the unmatched right rows in ascending order."""
    marks = marks if marks is not None else {}
    nl, nr = left.nrow, right.nrow
    with munge_stats.stage(marks, "factorize"):
        l_cols, r_cols, sizes = [], [], []
        l_dead = np.zeros(nl, bool)
        r_dead = np.zeros(nr, bool)
        for b in by:
            lc, rc, size, ld, rd = _factorize_key_column(
                left.vec(b), right.vec(b), nl, nr)
            l_cols.append(lc)
            r_cols.append(rc)
            sizes.append(size)
            l_dead |= ld
            r_dead |= rd
    with munge_stats.stage(marks, "combine"):
        comb_l = l_cols[0].copy()
        comb_r = r_cols[0].copy()
        size = sizes[0]
        for i in range(1, len(by)):
            if size * sizes[i] >= (1 << 62):
                # compact the running codes before the radix product could
                # overflow int64 (joint unique keeps cross-side equality)
                u, inv = np.unique(np.concatenate([comb_l, comb_r]),
                                   return_inverse=True)
                comb_l, comb_r = inv[:nl].astype(np.int64), \
                    inv[nl:].astype(np.int64)
                size = len(u)
            comb_l = comb_l * sizes[i] + l_cols[i]
            comb_r = comb_r * sizes[i] + r_cols[i]
            size *= sizes[i]
        if size > max(2 * (nl + nr), 1 << 20):
            # compact so the direct-address join table below stays a few
            # MB instead of O(radix-product); compacted codes are < nl+nr
            u, inv = np.unique(np.concatenate([comb_l, comb_r]),
                               return_inverse=True)
            comb_l, comb_r = inv[:nl].astype(np.int64), \
                inv[nl:].astype(np.int64)
            size = len(u)
    with munge_stats.stage(marks, "match"):
        r_alive = np.flatnonzero(~r_dead)
        rs = comb_r[r_alive]
        r_order = np.argsort(rs, kind="stable")  # ties keep right-row order
        rs_sorted = rs[r_order]
        r_orig = r_alive[r_order]

        # direct-address join table over the (bounded) code space: per-key
        # run start + length in rs_sorted — one O(1) gather per left row
        # instead of a binary search (the radix-join payoff)
        bnd = (np.flatnonzero(np.r_[True, rs_sorted[1:] != rs_sorted[:-1]])
               if rs_sorted.size else np.empty(0, np.int64))
        table_lo = np.zeros(max(int(size), 1), np.int64)
        table_cnt = np.zeros(max(int(size), 1), np.int64)
        if bnd.size:
            ru = rs_sorted[bnd]
            table_lo[ru] = bnd
            table_cnt[ru] = np.r_[bnd[1:], len(rs_sorted)] - bnd
        lo = table_lo[comb_l]
        counts = table_cnt[comb_l]
        counts[l_dead] = 0
        matched_l = counts > 0

        out_counts = np.where(matched_l, counts, 1 if all_x else 0)
        total = int(out_counts.sum())
        li = np.repeat(np.arange(nl, dtype=np.int64), out_counts)
        starts = np.cumsum(out_counts) - out_counts
        within = np.arange(total, dtype=np.int64) - np.repeat(starts,
                                                              out_counts)
        m_rep = np.repeat(matched_l, out_counts)
        ri = np.full(total, -1, np.int64)
        if r_orig.size:
            gather = np.minimum(np.repeat(lo, out_counts) + within,
                                len(r_orig) - 1)
            ri[m_rep] = r_orig[gather[m_rep]]
        if all_y:
            r_matched = np.zeros(nr, bool)
            if r_orig.size:
                l_present = np.zeros(max(int(size), 1), bool)
                l_present[comb_l[matched_l]] = True
                r_matched[r_alive] = l_present[rs]
            extra = np.flatnonzero(~r_matched)
            li = np.concatenate([li, np.full(len(extra), -1, np.int64)])
            ri = np.concatenate([ri, extra.astype(np.int64)])
    return li, ri


def merge(left: Frame, right: Frame, by: Optional[Sequence[str]] = None,
          all_x: bool = False, all_y: bool = False) -> Frame:
    """`AstMerge` — hash/radix join on shared key columns. Inner by default;
    all_x ⇒ left outer, all_y ⇒ right outer (h2o.merge semantics)."""
    if by is None:
        by = [n for n in left.names if n in right.names]
    if not by:
        raise ValueError("merge: no common key columns")
    marks: Dict[str, float] = {}
    legacy = munge_stats.legacy_enabled()
    with munge_stats.op("merge", left.nrow + right.nrow, stages=marks,
                        path="legacy" if legacy else "vectorized") as _rec:
        if legacy:
            with munge_stats.stage(marks, "match"):
                li, ri = _join_indices_legacy(left, right, by, all_x, all_y)
        else:
            li, ri = _join_indices_radix(left, right, by, all_x, all_y,
                                         marks)
        with munge_stats.stage(marks, "assemble"):
            out = _assemble_merge(left, right, by, li, ri)
        _rec["rows_out"] = out.nrow
    return out


def _take_or_na(v: Vec, idx: np.ndarray) -> Vec:
    """`v.take(max(idx, 0))` that survives a 0-row source: when the frame
    side is empty every index is -1 (pure NA fill), so synthesize the NA
    column instead of gathering row 0 of nothing (the seed crashed here).
    The fill keeps the source dtype — epoch-ms 'time' columns are f64."""
    if len(v) == 0:
        n = len(idx)
        if v.type == "enum":
            return Vec(np.full(n, -1, np.int32), "enum", domain=v.domain)
        if v.type == "string":
            return Vec(None, "string",
                       strings=np.full(n, None, dtype=object))
        return Vec(np.full(n, np.nan, np.asarray(v.data).dtype), v.type)
    return v.take(np.maximum(idx, 0))


def _assemble_merge(left: Frame, right: Frame, by: Sequence[str],
                    li: np.ndarray, ri: np.ndarray) -> Frame:
    """Gather the output columns from (li, ri) row indices (-1 ⇒ NA fill).
    ONE assembly shared by the radix and legacy index builders, so the
    comparator can only differ in match order — never in column fill."""
    out: Dict[str, Vec] = {}
    for n in left.names:
        if n in by:
            # key columns: take from whichever side matched (right-outer rows
            # must keep their join key — h2o.merge/R merge semantics)
            lv = _take_or_na(left.vec(n), li)
            if (li < 0).any():
                rv = _take_or_na(right.vec(n), ri)

                def _values(v: Vec) -> np.ndarray:
                    # enum → labels, numeric → numbers; per-side so a type
                    # mismatch between sides can't index labels with floats
                    if v.type == "enum":
                        dom = np.asarray((v.domain or []) + [None], dtype=object)
                        return dom[np.asarray(v.data, np.int64)]
                    return v.numeric_np().astype(object)

                if lv.type == "enum" or rv.type == "enum":
                    lvals, rvals = _values(lv), _values(rv)
                    if lv.type != rv.type:  # mixed enum/numeric keys: stringify
                        def _s(a):
                            return np.asarray(
                                [None if x is None else str(x) for x in a], object)
                        lvals, rvals = _s(lvals), _s(rvals)
                    lbl = np.where(li < 0, rvals, lvals)
                    out[n] = Vec.from_numpy(lbl.astype(object))
                else:
                    merged = np.where(li < 0, rv.numeric_np(), lv.numeric_np())
                    # keep the left side's dtype: f32 for real/int (seed
                    # behavior), f64 for epoch-ms time keys (an f32 cast
                    # would lose ~minutes of precision)
                    out[n] = Vec(merged.astype(np.asarray(lv.data).dtype),
                                 lv.type)
            else:
                out[n] = lv
            continue
        v = _take_or_na(left.vec(n), li)
        out[n] = _mask_vec(v, li < 0)
    for n in right.names:
        if n in by:
            continue
        nn = n
        while nn in out:
            nn += "0"
        v = _take_or_na(right.vec(n), ri)
        out[nn] = _mask_vec(v, ri < 0)
    return Frame(out)


def _mask_vec(v: Vec, na_mask: np.ndarray) -> Vec:
    if not na_mask.any():
        return v
    if v.type == "enum":
        d = np.asarray(v.data).copy()
        d[na_mask] = -1
        return Vec(d, "enum", domain=v.domain)
    if v.type == "string":
        s = v.to_numpy().copy()
        s[na_mask] = None
        return Vec(None, "string", strings=s)
    src_dtype = np.asarray(v.data).dtype
    d = np.asarray(v.data, np.float64).copy()
    d[na_mask] = np.nan
    # preserve the source dtype: the seed's unconditional f32 cast silently
    # corrupted f64 epoch-ms 'time' columns on every outer merge
    return Vec(d.astype(src_dtype), v.type)


def quantile(frame: Frame, prob: Sequence[float], combine_method: str = "interpolate") -> Frame:
    """`AstQtile` / `hex/quantile/Quantile.java` — per-column quantiles."""
    probs = np.asarray(list(prob), np.float64)
    out = {"Probs": probs}
    for n in frame.names:
        v = frame.vec(n)
        if v.type not in ("real", "int"):
            continue
        col = v.numeric_np()
        col = col[~np.isnan(col)]
        method = "linear" if combine_method == "interpolate" else "lower"
        out[f"{n}Quantiles"] = (
            np.quantile(col, probs, method=method) if col.size else np.full(len(probs), np.nan)
        )
    return Frame.from_dict(out)


def _factorize_labels(v: Vec):
    """(codes, levels) of one column for the factorize+scatter reshapers:
    codes are positions into `levels` with -1 for NA; `levels` is an object
    array in exactly the seed's sorted-set order (python `sorted` for enum
    labels — equal to code-point order — and ascending numeric order).
    Unused enum domain levels are excluded, like the seed's set-of-labels."""
    if v.type == "enum":
        codes_raw = np.asarray(v.data, np.int64)
        dom = v.domain or []
        if not dom:  # all-NA enum column interns with an empty domain
            return (np.full(codes_raw.shape, -1, np.int64),
                    np.empty(0, dtype=object))
        present = np.unique(codes_raw[codes_raw >= 0])
        levels = sorted(dom[c] for c in present)
        pos = {lbl: i for i, lbl in enumerate(levels)}
        remap = np.full(len(dom), -1, np.int64)
        for c in present:
            remap[c] = pos[dom[c]]
        codes = np.where(codes_raw >= 0, remap[np.maximum(codes_raw, 0)], -1)
        return codes, np.asarray(levels, dtype=object)
    col = v.numeric_np()
    valid = ~np.isnan(col)
    uniq = np.unique(col[valid])
    codes = np.full(len(col), -1, np.int64)
    if uniq.size:
        codes[valid] = np.searchsorted(uniq, col[valid])
    return codes, uniq.astype(object)


def table(frame: Frame, dense: bool = True) -> Frame:
    """`AstTable` — value counts of 1–2 categorical/int columns."""
    legacy = munge_stats.legacy_enabled()  # read ONCE: tag and dispatch
    with munge_stats.op("table", frame.nrow,
                        path="legacy" if legacy else "vectorized") as _rec:
        out = _table_impl(frame, dense, legacy)
        _rec["rows_out"] = out.nrow
    return out


def _table_impl(frame: Frame, dense: bool, legacy: bool) -> Frame:
    vs = frame.vecs()
    if len(vs) == 1:
        v = vs[0]
        if v.type == "enum":
            codes = np.asarray(v.data)
            counts = np.bincount(codes[codes >= 0], minlength=v.nlevels)
            return Frame.from_dict({
                frame.names[0]: np.asarray(v.domain, dtype=object),
                "Count": counts.astype(np.float64),
            })
        col = v.numeric_np()
        u, cnt = np.unique(col[~np.isnan(col)], return_counts=True)
        return Frame.from_dict({frame.names[0]: u, "Count": cnt.astype(np.float64)})
    if len(vs) == 2:
        # two-column cross-tab, long format (col1, col2, Counts) — the
        # AstTable 2-arg form
        t1 = "enum" if vs[0].type == "enum" else None
        t2 = "enum" if vs[1].type == "enum" else None
        types = {k: v for k, v in
                 [(frame.names[0], t1), (frame.names[1], t2)] if v}
        if legacy:
            return _table2_legacy(frame, vs, types)
        ca, la = _factorize_labels(vs[0])
        cb, lb = _factorize_labels(vs[1])
        keep = (ca >= 0) & (cb >= 0)
        nb = max(len(lb), 1)
        comb = ca[keep] * nb + cb[keep]
        u, cnt = np.unique(comb, return_counts=True)
        # ascending combined code == (a level, b level) lexicographic ==
        # the seed's sorted(pairs) order
        return Frame.from_dict(
            {frame.names[0]: la[u // nb] if len(la) else
             np.empty(0, dtype=object),
             frame.names[1]: lb[u % nb] if len(lb) else
             np.empty(0, dtype=object),
             "Counts": cnt.astype(np.float64)},
            column_types=types)
    raise ValueError("table: at most 2 columns")


def _table2_legacy(frame: Frame, vs, types) -> Frame:
    def _labels(v):
        if v.type == "enum":
            codes = np.asarray(v.data)
            return np.asarray(
                [v.domain[c] if c >= 0 else None for c in codes],
                dtype=object)
        return v.numeric_np().astype(object)

    a = _labels(vs[0])
    b = _labels(vs[1])
    keep = np.asarray([x is not None and x == x and y is not None
                       and y == y for x, y in zip(a, b)])
    pairs: Dict = {}
    for x, y in zip(a[keep], b[keep]):
        pairs[(x, y)] = pairs.get((x, y), 0) + 1
    keys = sorted(pairs)
    return Frame.from_dict(
        {frame.names[0]: np.asarray([k[0] for k in keys], dtype=object),
         frame.names[1]: np.asarray([k[1] for k in keys], dtype=object),
         "Counts": np.asarray([pairs[k] for k in keys], np.float64)},
        column_types=types)


def ifelse(cond: np.ndarray, yes, no) -> np.ndarray:
    return np.where(cond, yes, no)


def melt(frame: Frame, id_vars: List[str], value_vars: Optional[List[str]],
         var_name: str = "variable", value_name: str = "value",
         skipna: bool = False) -> Frame:
    """`AstMelt` — wide → long: one output row per (row, value column)."""
    value_vars = value_vars or [n for n in frame.names if n not in id_vars]
    n = frame.nrow
    k = len(value_vars)
    out: Dict[str, np.ndarray] = {}
    types: Dict[str, str] = {}
    for idc in id_vars:
        v = frame.vec(idc)
        if v.type == "enum":
            lab = np.asarray([v.domain[c] if c >= 0 else None
                              for c in np.asarray(v.data)], dtype=object)
            out[idc] = np.tile(lab, k)
            types[idc] = "enum"
        else:
            out[idc] = np.tile(v.numeric_np(), k)
    out[var_name] = np.repeat(np.asarray(value_vars, dtype=object), n)
    types[var_name] = "enum"
    vals = np.concatenate([frame.vec(c).numeric_np() for c in value_vars])
    out[value_name] = vals
    fr = Frame.from_dict(out, column_types=types)
    if skipna:
        fr = fr.take(np.nonzero(~np.isnan(vals))[0])
    return fr


def pivot(frame: Frame, index: str, column: str, value: str) -> Frame:
    """`AstPivot` — long → wide: rows keyed by `index`, one output column
    per level of `column`, cells from `value` (last write wins, NaN where
    absent)."""
    legacy = munge_stats.legacy_enabled()
    with munge_stats.op("pivot", frame.nrow,
                        path="legacy" if legacy else "vectorized") as _rec:
        out = (_pivot_legacy if legacy else _pivot_vectorized)(
            frame, index, column, value)
        _rec["rows_out"] = out.nrow
    return out


def _pivot_vectorized(frame: Frame, index: str, column: str,
                      value: str) -> Frame:
    """Factorize both key columns, then ONE flat scatter into the grid.
    Last write wins exactly like the seed's row loop: `np.maximum.at` over
    row ordinals picks the LAST valid row per cell (unbuffered, so
    duplicate cells are well-defined — plain fancy assignment is not)."""
    iv, cv = frame.vec(index), frame.vec(column)
    icodes, ilevels = _factorize_labels(iv)
    ccodes, clevels = _factorize_labels(cv)
    vals = frame.vec(value).numeric_np()
    n_i, n_c = len(ilevels), len(clevels)
    grid = np.full((n_i, n_c), np.nan)
    valid = (icodes >= 0) & (ccodes >= 0)
    if valid.any() and n_i and n_c:
        lin = icodes[valid] * n_c + ccodes[valid]
        vv = vals[valid]
        last = np.full(n_i * n_c, -1, np.int64)
        np.maximum.at(last, lin, np.arange(len(lin), dtype=np.int64))
        cells = np.flatnonzero(last >= 0)
        grid.flat[cells] = vv[last[cells]]
    out: Dict[str, np.ndarray] = {index: ilevels}
    types = {index: "enum"} if iv.type == "enum" else {}
    for j, cname in enumerate(clevels.tolist()):
        out[str(cname)] = grid[:, j]
    return Frame.from_dict(out, column_types=types)


def _pivot_legacy(frame: Frame, index: str, column: str, value: str) -> Frame:
    iv, cv = frame.vec(index), frame.vec(column)

    def _labels(v):
        if v.type == "enum":
            return np.asarray([v.domain[c] if c >= 0 else None
                               for c in np.asarray(v.data)], dtype=object)
        return v.numeric_np().astype(object)

    ilab, clab = _labels(iv), _labels(cv)
    vals = frame.vec(value).numeric_np()

    def _sorted_levels(lab):
        lv = {x for x in lab if x is not None and x == x}
        try:
            return sorted(lv)          # natural order (numeric keys ascend)
        except TypeError:
            return sorted(lv, key=str)

    uidx = _sorted_levels(ilab)
    ucol = _sorted_levels(clab)
    ipos = {x: i for i, x in enumerate(uidx)}
    cpos = {x: i for i, x in enumerate(ucol)}
    grid = np.full((len(uidx), len(ucol)), np.nan)
    for r in range(len(vals)):
        if ilab[r] in ipos and clab[r] in cpos:
            grid[ipos[ilab[r]], cpos[clab[r]]] = vals[r]
    out: Dict[str, np.ndarray] = {
        index: np.asarray(uidx, dtype=object)}
    types = {index: "enum"} if iv.type == "enum" else {}
    for j, cname in enumerate(ucol):
        out[str(cname)] = grid[:, j]
    return Frame.from_dict(out, column_types=types)
