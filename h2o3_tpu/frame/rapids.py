"""Rapids subset — dataframe munging ops.

Reference parity: `h2o-core/src/main/java/water/rapids/` — the Lisp-AST
interpreter (`Rapids.java`, `Session.java`) and its ~100 `ast/prims/**` ops;
the ones replicated here are the workhorses the reference's own tests lean
on: `AstGroup` (group-by aggregates), `AstMerge` (radix join),
`AstDdply`-style application, quantiles, value counts, ifelse/apply basics.

The client-server indirection is collapsed (no Lisp strings, no /99/Rapids
POST): ops execute eagerly as numpy reductions — at frame-munging scale the
host is the right place; device time is reserved for training loops.
GroupBy mirrors `h2o-py/h2o/group_by.py`'s builder surface
(`fr.group_by(...).sum().mean().get_frame()`).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from .frame import Frame
from .vec import Vec

_AGGS = ("count", "sum", "mean", "min", "max", "sd", "var", "median", "mode", "first", "last")


class GroupBy:
    """`h2o-py/h2o/group_by.py` builder over `AstGroup` semantics."""

    def __init__(self, frame: Frame, by: Union[str, Sequence[str]]):
        self.frame = frame
        self.by = [by] if isinstance(by, str) else list(by)
        self._aggs: List = []  # (op, col, na)

    def _add(self, op, col, na):
        cols = col if isinstance(col, (list, tuple)) else [col]
        for c in cols:
            self._aggs.append((op, c, na))
        return self

    def count(self, na="all"):
        self._aggs.append(("count", None, na))
        return self

    def sum(self, col=None, na="all"):
        return self._add("sum", col or self._numeric_cols(), na)

    def mean(self, col=None, na="all"):
        return self._add("mean", col or self._numeric_cols(), na)

    def min(self, col=None, na="all"):
        return self._add("min", col or self._numeric_cols(), na)

    def max(self, col=None, na="all"):
        return self._add("max", col or self._numeric_cols(), na)

    def sd(self, col=None, na="all"):
        return self._add("sd", col or self._numeric_cols(), na)

    def var(self, col=None, na="all"):
        return self._add("var", col or self._numeric_cols(), na)

    def median(self, col=None, na="all"):
        return self._add("median", col or self._numeric_cols(), na)

    def mode(self, col=None, na="all"):
        return self._add("mode", col or self._numeric_cols(), na)

    def _numeric_cols(self):
        return [n for n in self.frame.names
                if n not in self.by and self.frame.vec(n).type in ("real", "int")]

    def get_frame(self) -> Frame:
        fr = self.frame
        keys = [fr.vec(b) for b in self.by]
        key_codes = []
        key_domains = []
        for v in keys:
            if v.type == "enum":
                key_codes.append(np.asarray(v.data, np.int64))
                key_domains.append(np.asarray(v.domain, dtype=object))
            else:
                col = v.numeric_np()
                uniq, inv = np.unique(col, return_inverse=True)
                key_codes.append(inv.astype(np.int64))
                key_domains.append(uniq)
        combined = key_codes[0].copy()
        mult = 1
        sizes = [len(d) for d in key_domains]
        for i in range(1, len(key_codes)):
            mult *= sizes[i - 1]
            combined = combined + key_codes[i] * mult  # little-endian mixed radix
        groups, ginv = np.unique(combined, return_inverse=True)
        G = len(groups)

        out: Dict[str, np.ndarray] = {}
        for i, b in enumerate(self.by):
            idx = (groups // int(np.prod(sizes[:i]) if i else 1)) % sizes[i]
            dom = key_domains[i]
            vals = dom[idx]
            out[b] = vals
        order = np.lexsort([out[b] for b in reversed(self.by)])

        # vectorized per-group reductions: moments via bincount-with-weights,
        # order statistics via one sort + reduceat — O(n log n), never O(G·n)
        sort_cache: Dict[str, tuple] = {}

        def _sorted(colname, c):
            if colname not in sort_cache:
                valid = ~np.isnan(c)
                gv = ginv[valid]
                cv = c[valid]
                order = np.lexsort((cv, gv))
                gs, cs = gv[order], cv[order]
                starts = np.flatnonzero(np.r_[True, gs[1:] != gs[:-1]])
                sort_cache[colname] = (gs, cs, starts)
            return sort_cache[colname]

        for op, col, na in self._aggs:
            if op == "count":
                out["nrow"] = np.bincount(ginv, minlength=G).astype(np.float64)
                continue
            c = fr.vec(col).numeric_np()
            name = f"{op}_{col}"
            agg = np.full(G, np.nan)
            valid = ~np.isnan(c)  # AstGroup skips NAs inside aggregates
            gv = ginv[valid]
            cv = c[valid]
            cnt = np.bincount(gv, minlength=G).astype(np.float64)
            nz = cnt > 0
            if op in ("sum", "mean", "sd", "var"):
                s1 = np.bincount(gv, weights=cv, minlength=G)
                if op == "sum":
                    agg[nz] = s1[nz]
                elif op == "mean":
                    agg[nz] = s1[nz] / cnt[nz]
                else:
                    s2 = np.bincount(gv, weights=cv * cv, minlength=G)
                    mean = np.where(nz, s1 / np.maximum(cnt, 1), 0.0)
                    ss = np.maximum(s2 - cnt * mean * mean, 0.0)
                    var = np.where(cnt > 1, ss / np.maximum(cnt - 1, 1), 0.0)
                    agg[nz] = np.sqrt(var[nz]) if op == "sd" else var[nz]
            elif op in ("min", "max"):
                gs, cs, starts = _sorted(col, c)
                present = np.unique(gs)
                ends = np.r_[starts[1:], len(cs)]
                vals = cs[starts] if op == "min" else cs[ends - 1]
                agg[present] = vals
            elif op == "median":
                gs, cs, starts = _sorted(col, c)
                present = np.unique(gs)
                ends = np.r_[starts[1:], len(cs)]
                lens = ends - starts
                lo = starts + (lens - 1) // 2
                hi = starts + lens // 2
                agg[present] = 0.5 * (cs[lo] + cs[hi])
            elif op == "mode":
                # mode = longest run within (group, value)-sorted order
                gs, cs, starts = _sorted(col, c)
                runs = np.flatnonzero(
                    np.r_[True, (gs[1:] != gs[:-1]) | (cs[1:] != cs[:-1])]
                )
                run_ends = np.r_[runs[1:], len(cs)]
                run_len = run_ends - runs
                run_grp = gs[runs]
                run_val = cs[runs]
                best_order = np.lexsort((run_len, run_grp))
                gb, lb, vb = run_grp[best_order], run_len[best_order], run_val[best_order]
                last = np.flatnonzero(np.r_[gb[1:] != gb[:-1], True])
                agg[gb[last]] = vb[last]
            out[name] = agg

        return Frame.from_dict({k: np.asarray(v)[order] for k, v in out.items()})


def merge(left: Frame, right: Frame, by: Optional[Sequence[str]] = None,
          all_x: bool = False, all_y: bool = False) -> Frame:
    """`AstMerge` — hash/radix join on shared key columns. Inner by default;
    all_x ⇒ left outer, all_y ⇒ right outer (h2o.merge semantics)."""
    if by is None:
        by = [n for n in left.names if n in right.names]
    if not by:
        raise ValueError("merge: no common key columns")

    def keytuple(fr: Frame):
        cols = []
        for b in by:
            v = fr.vec(b)
            if v.type == "enum":
                dom = np.asarray(v.domain + [None], dtype=object)
                cols.append(dom[np.asarray(v.data)])
            else:
                cols.append(v.numeric_np())
        return list(zip(*[c.tolist() for c in cols])) if cols else []

    lk = keytuple(left)
    rk = keytuple(right)
    rmap: Dict = {}
    for j, k in enumerate(rk):
        rmap.setdefault(k, []).append(j)
    li, ri = [], []
    matched_r = set()
    for i, k in enumerate(lk):
        js = rmap.get(k)
        if js:
            for j in js:
                li.append(i)
                ri.append(j)
                matched_r.add(j)
        elif all_x:
            li.append(i)
            ri.append(-1)
    if all_y:
        for j in range(len(rk)):
            if j not in matched_r:
                li.append(-1)
                ri.append(j)
    li = np.asarray(li, np.int64)
    ri = np.asarray(ri, np.int64)

    out: Dict[str, Vec] = {}
    for n in left.names:
        if n in by:
            # key columns: take from whichever side matched (right-outer rows
            # must keep their join key — h2o.merge/R merge semantics)
            lv = left.vec(n).take(np.maximum(li, 0))
            if (li < 0).any():
                rv = right.vec(n).take(np.maximum(ri, 0))

                def _values(v: Vec) -> np.ndarray:
                    # enum → labels, numeric → numbers; per-side so a type
                    # mismatch between sides can't index labels with floats
                    if v.type == "enum":
                        dom = np.asarray((v.domain or []) + [None], dtype=object)
                        return dom[np.asarray(v.data, np.int64)]
                    return v.numeric_np().astype(object)

                if lv.type == "enum" or rv.type == "enum":
                    lvals, rvals = _values(lv), _values(rv)
                    if lv.type != rv.type:  # mixed enum/numeric keys: stringify
                        def _s(a):
                            return np.asarray(
                                [None if x is None else str(x) for x in a], object)
                        lvals, rvals = _s(lvals), _s(rvals)
                    lbl = np.where(li < 0, rvals, lvals)
                    out[n] = Vec.from_numpy(lbl.astype(object))
                else:
                    merged = np.where(li < 0, rv.numeric_np(), lv.numeric_np())
                    out[n] = Vec(merged.astype(np.float32), lv.type)
            else:
                out[n] = lv
            continue
        v = left.vec(n).take(np.maximum(li, 0))
        out[n] = _mask_vec(v, li < 0)
    for n in right.names:
        if n in by:
            continue
        nn = n
        while nn in out:
            nn += "0"
        v = right.vec(n).take(np.maximum(ri, 0))
        out[nn] = _mask_vec(v, ri < 0)
    return Frame(out)


def _mask_vec(v: Vec, na_mask: np.ndarray) -> Vec:
    if not na_mask.any():
        return v
    if v.type == "enum":
        d = np.asarray(v.data).copy()
        d[na_mask] = -1
        return Vec(d, "enum", domain=v.domain)
    if v.type == "string":
        s = v.to_numpy().copy()
        s[na_mask] = None
        return Vec(None, "string", strings=s)
    d = np.asarray(v.data, np.float64).copy()
    d[na_mask] = np.nan
    return Vec(d.astype(np.float32), v.type)


def quantile(frame: Frame, prob: Sequence[float], combine_method: str = "interpolate") -> Frame:
    """`AstQtile` / `hex/quantile/Quantile.java` — per-column quantiles."""
    probs = np.asarray(list(prob), np.float64)
    out = {"Probs": probs}
    for n in frame.names:
        v = frame.vec(n)
        if v.type not in ("real", "int"):
            continue
        col = v.numeric_np()
        col = col[~np.isnan(col)]
        method = "linear" if combine_method == "interpolate" else "lower"
        out[f"{n}Quantiles"] = (
            np.quantile(col, probs, method=method) if col.size else np.full(len(probs), np.nan)
        )
    return Frame.from_dict(out)


def table(frame: Frame, dense: bool = True) -> Frame:
    """`AstTable` — value counts of 1–2 categorical/int columns."""
    vs = frame.vecs()
    if len(vs) == 1:
        v = vs[0]
        if v.type == "enum":
            codes = np.asarray(v.data)
            counts = np.bincount(codes[codes >= 0], minlength=v.nlevels)
            return Frame.from_dict({
                frame.names[0]: np.asarray(v.domain, dtype=object),
                "Count": counts.astype(np.float64),
            })
        col = v.numeric_np()
        u, cnt = np.unique(col[~np.isnan(col)], return_counts=True)
        return Frame.from_dict({frame.names[0]: u, "Count": cnt.astype(np.float64)})
    if len(vs) == 2:
        # two-column cross-tab, long format (col1, col2, Counts) — the
        # AstTable 2-arg form
        def _labels(v):
            if v.type == "enum":
                codes = np.asarray(v.data)
                return np.asarray(
                    [v.domain[c] if c >= 0 else None for c in codes],
                    dtype=object)
            return v.numeric_np().astype(object)

        a = _labels(vs[0])
        b = _labels(vs[1])
        keep = np.asarray([x is not None and x == x and y is not None
                           and y == y for x, y in zip(a, b)])
        pairs: Dict = {}
        for x, y in zip(a[keep], b[keep]):
            pairs[(x, y)] = pairs.get((x, y), 0) + 1
        keys = sorted(pairs)
        t1 = "enum" if vs[0].type == "enum" else None
        t2 = "enum" if vs[1].type == "enum" else None
        return Frame.from_dict(
            {frame.names[0]: np.asarray([k[0] for k in keys], dtype=object),
             frame.names[1]: np.asarray([k[1] for k in keys], dtype=object),
             "Counts": np.asarray([pairs[k] for k in keys], np.float64)},
            column_types={k: v for k, v in
                          [(frame.names[0], t1), (frame.names[1], t2)] if v})
    raise ValueError("table: at most 2 columns")


def ifelse(cond: np.ndarray, yes, no) -> np.ndarray:
    return np.where(cond, yes, no)


def melt(frame: Frame, id_vars: List[str], value_vars: Optional[List[str]],
         var_name: str = "variable", value_name: str = "value",
         skipna: bool = False) -> Frame:
    """`AstMelt` — wide → long: one output row per (row, value column)."""
    value_vars = value_vars or [n for n in frame.names if n not in id_vars]
    n = frame.nrow
    k = len(value_vars)
    out: Dict[str, np.ndarray] = {}
    types: Dict[str, str] = {}
    for idc in id_vars:
        v = frame.vec(idc)
        if v.type == "enum":
            lab = np.asarray([v.domain[c] if c >= 0 else None
                              for c in np.asarray(v.data)], dtype=object)
            out[idc] = np.tile(lab, k)
            types[idc] = "enum"
        else:
            out[idc] = np.tile(v.numeric_np(), k)
    out[var_name] = np.repeat(np.asarray(value_vars, dtype=object), n)
    types[var_name] = "enum"
    vals = np.concatenate([frame.vec(c).numeric_np() for c in value_vars])
    out[value_name] = vals
    fr = Frame.from_dict(out, column_types=types)
    if skipna:
        fr = fr.take(np.nonzero(~np.isnan(vals))[0])
    return fr


def pivot(frame: Frame, index: str, column: str, value: str) -> Frame:
    """`AstPivot` — long → wide: rows keyed by `index`, one output column
    per level of `column`, cells from `value` (last write wins, NaN where
    absent)."""
    iv, cv = frame.vec(index), frame.vec(column)

    def _labels(v):
        if v.type == "enum":
            return np.asarray([v.domain[c] if c >= 0 else None
                               for c in np.asarray(v.data)], dtype=object)
        return v.numeric_np().astype(object)

    ilab, clab = _labels(iv), _labels(cv)
    vals = frame.vec(value).numeric_np()

    def _sorted_levels(lab):
        lv = {x for x in lab if x is not None and x == x}
        try:
            return sorted(lv)          # natural order (numeric keys ascend)
        except TypeError:
            return sorted(lv, key=str)

    uidx = _sorted_levels(ilab)
    ucol = _sorted_levels(clab)
    ipos = {x: i for i, x in enumerate(uidx)}
    cpos = {x: i for i, x in enumerate(ucol)}
    grid = np.full((len(uidx), len(ucol)), np.nan)
    for r in range(len(vals)):
        if ilab[r] in ipos and clab[r] in cpos:
            grid[ipos[ilab[r]], cpos[clab[r]]] = vals[r]
    out: Dict[str, np.ndarray] = {
        index: np.asarray(uidx, dtype=object)}
    types = {index: "enum"} if iv.type == "enum" else {}
    for j, cname in enumerate(ucol):
        out[str(cname)] = grid[:, j]
    return Frame.from_dict(out, column_types=types)
