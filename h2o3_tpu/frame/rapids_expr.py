"""Rapids expression interpreter — the Lisp strings `/99/Rapids` accepts.

Reference parity: `h2o-core/src/main/java/water/rapids/Rapids.java` (the
recursive-descent sexpr parser) + `water/rapids/ast/prims/**` (the prim
table). The h2o-py client compiles every Frame operation into one of these
strings; this module implements the subset the Python surface emits most:
arithmetic/comparison binops, slicing (`cols`/`rows`), `cbind`/`rbind`,
reducers (`mean`/`sum`/`sd`/`min`/`max`), `quantile`, `table`, `merge`,
`asfactor`/`as.numeric`, `ifelse`, `unique`, `assign`/`tmp` naming.

Number/string/list literals follow the reference grammar: `[1 2 3]` numeric
list, `["a" "b"]` string list, `(op arg …)` application, bare tokens are
DKV keys or prim names.
"""

from __future__ import annotations

from typing import Any, List

import numpy as np

from . import rapids as rapids_ops
from .frame import Frame
from .vec import Vec


# -- tokenizer / parser ------------------------------------------------------
def _tokenize(s: str) -> List[str]:
    out, i, n = [], 0, len(s)
    while i < n:
        c = s[i]
        if c.isspace():
            i += 1
        elif c in "()[]":
            out.append(c)
            i += 1
        elif c in "\"'":
            j = i + 1
            while j < n and s[j] != c:
                j += 2 if s[j] == "\\" else 1
            out.append(s[i : j + 1])
            i = j + 1
        else:
            j = i
            while j < n and not s[j].isspace() and s[j] not in "()[]":
                j += 1
            out.append(s[i:j])
            i = j
    return out


def _parse(tokens: List[str], pos: int = 0):
    t = tokens[pos]
    if t == "(":
        items = []
        pos += 1
        while tokens[pos] != ")":
            node, pos = _parse(tokens, pos)
            items.append(node)
        return ("call", items), pos + 1
    if t == "[":
        items = []
        pos += 1
        while tokens[pos] != "]":
            node, pos = _parse(tokens, pos)
            items.append(node)
        return ("list", items), pos + 1
    if t and t[0] in "\"'":
        return ("str", t[1:-1]), pos + 1
    try:
        return ("num", float(t)), pos + 1
    except ValueError:
        return ("sym", t), pos + 1


class RapidsSession:
    """`water.rapids.Session` — holds temp frames across expressions."""

    def __init__(self, dkv=None):
        if dkv is None:
            from ..runtime.dkv import DKV as dkv
        self.dkv = dkv

    # -- evaluation ----------------------------------------------------------
    def execute(self, expr: str):
        ast, pos = _parse(_tokenize(expr))
        return self._eval(ast)

    def _eval(self, node) -> Any:
        kind, val = node
        if kind == "num":
            return val
        if kind == "str":
            return val
        if kind == "list":
            return [self._eval(v) for v in val]
        if kind == "sym":
            obj = self.dkv.get(val)
            if obj is not None:
                return obj
            return val  # prim name or bare symbol
        # call
        op = val[0][1] if val[0][0] == "sym" else self._eval(val[0])
        args = [self._eval(a) for a in val[1:]]
        return self._apply(op, args)

    # -- prims ---------------------------------------------------------------
    def _apply(self, op: str, a: List[Any]):
        import operator

        binops = {
            "+": operator.add, "-": operator.sub, "*": operator.mul,
            "/": operator.truediv, ">": operator.gt, "<": operator.lt,
            ">=": operator.ge, "<=": operator.le, "==": operator.eq,
            "!=": operator.ne,
        }
        if op in binops:
            x, y = a
            if isinstance(x, Frame) or isinstance(y, Frame):
                return binops[op](x, y) if isinstance(x, Frame) else binops[op](y, x)
            return binops[op](x, y)
        if op in ("assign", "tmp="):
            key, value = a
            if isinstance(value, Frame):
                value.key = str(key)
            self.dkv.put(str(key), value)
            return value
        if op == "rm":
            self.dkv.remove(str(a[0]))
            return None
        if op == "cols":
            fr, sel = a
            names = (
                [fr.names[int(i)] for i in sel]
                if all(isinstance(i, float) for i in sel)
                else [str(s) for s in sel]
            ) if isinstance(sel, list) else (
                [fr.names[int(sel)]] if isinstance(sel, float) else [str(sel)]
            )
            return fr[names]
        if op == "rows":
            fr, sel = a
            if isinstance(sel, Frame):  # boolean mask frame
                mask = sel._col0().astype(bool)
                return fr.take(np.nonzero(mask)[0])
            idx = np.asarray([int(i) for i in (sel if isinstance(sel, list) else [sel])])
            return fr.take(idx)
        if op == "cbind":
            out = a[0]
            for fr in a[1:]:
                out = out.cbind(fr)
            return out
        if op == "rbind":
            out = a[0]
            for fr in a[1:]:
                out = out.rbind(fr)
            return out
        if op in ("mean", "sum", "sd", "min", "max", "median"):
            fr = a[0]
            col = fr._col0() if isinstance(fr, Frame) else np.asarray(fr)
            fn = {"mean": np.nanmean, "sum": np.nansum, "sd": lambda c: np.nanstd(c, ddof=1),
                  "min": np.nanmin, "max": np.nanmax, "median": np.nanmedian}[op]
            return float(fn(col))
        if op == "quantile":
            fr, probs = a[0], a[1]
            return rapids_ops.quantile(fr, [float(p) for p in probs])
        if op == "table":
            return rapids_ops.table(a[0])
        if op == "merge":
            left, right = a[0], a[1]
            all_x = bool(a[2]) if len(a) > 2 else False
            all_y = bool(a[3]) if len(a) > 3 else False
            return rapids_ops.merge(left, right, all_x=all_x, all_y=all_y)
        if op == "as.factor":
            return a[0].asfactor()
        if op == "as.numeric":
            fr = a[0]
            v = fr.vecs()[0]
            return Frame({fr.names[0]: Vec(v.numeric_np(), "real")})
        if op == "unique":
            fr = a[0]
            v = fr.vecs()[0]
            if v.type == "enum":
                vals = sorted(set(np.asarray(v.data)[np.asarray(v.data) >= 0]))
                dom = v.domain
                return Frame.from_dict(
                    {fr.names[0]: np.asarray([dom[i] for i in vals], dtype=object)},
                    column_types={fr.names[0]: "enum"})
            u = np.unique(v.numeric_np())
            return Frame.from_dict({fr.names[0]: u[~np.isnan(u)]})
        if op == "ifelse":
            cond, yes, no = a
            c = cond._col0().astype(bool) if isinstance(cond, Frame) else np.asarray(cond, bool)
            yv = yes._col0() if isinstance(yes, Frame) else yes
            nv = no._col0() if isinstance(no, Frame) else no
            return Frame.from_dict({"ifelse": np.where(c, yv, nv)})
        if op == "nrow":
            return float(a[0].nrow)
        if op == "ncol":
            return float(a[0].ncol)
        if op == "colnames=":
            fr, _idx, names = a
            new = [str(n) for n in names]
            return Frame(dict(zip(new, fr.vecs())))
        if op == "tokenize":
            return a[0].tokenize(str(a[1]))
        def _truthy(v, default=True):
            """Rapids booleans arrive as TRUE/FALSE symbols or 0/1 numbers."""
            if v is None:
                return default
            if isinstance(v, str):
                return v.upper() in ("TRUE", "T", "1")
            if isinstance(v, (int, float)):
                return bool(v)
            raise ValueError(f"Rapids: expected a boolean, got {v!r}")

        if op == "sort":
            fr, sel = a[0], a[1]
            cols = [int(i) for i in (sel if isinstance(sel, list) else [sel])]
            asc = True
            if len(a) > 2:  # ascending flags per key column
                flags = a[2] if isinstance(a[2], list) else [a[2]]
                asc = [_truthy(f) for f in flags]
                if len(asc) == 1:
                    asc = asc[0]
            return fr.sort([fr.names[i] for i in cols], ascending=asc)
        if op == "h2o.impute":
            fr = a[0]
            col = int(a[1]) if len(a) > 1 else None
            method = str(a[2]).lower() if len(a) > 2 else "mean"
            by = None
            if len(a) > 4 and isinstance(a[4], list) and a[4]:
                by = [fr.names[int(i)] for i in a[4]]
            return fr.impute(fr.names[col] if col is not None and col >= 0 else None,
                             method=method, by=by)
        if op == "scale":
            # per-column numeric center/scale lists are a reference feature
            # this subset doesn't implement — reject rather than silently
            # substituting computed statistics
            for v in a[1:3]:
                if isinstance(v, list):
                    raise ValueError("Rapids scale: per-column center/scale "
                                     "lists not supported")
            center = _truthy(a[1] if len(a) > 1 else None)
            sc = _truthy(a[2] if len(a) > 2 else None)
            return a[0].scale(center=center, scale=sc)
        if op == "hist":
            return a[0].hist(int(a[1]) if len(a) > 1 else 20)
        if op == "cut":
            return a[0].cut([float(b) for b in a[1]])
        if op in ("year", "month", "day", "hour", "minute", "second",
                  "dayOfWeek"):
            return getattr(a[0], op)()
        if op in ("trim", "tolower", "toupper", "na.omit"):
            meth = {"na.omit": "na_omit"}.get(op, op)
            return getattr(a[0], meth)()
        if op in ("replacefirst", "replaceall"):
            fn = "sub" if op == "replacefirst" else "gsub"
            return getattr(a[0], fn)(str(a[1]), str(a[2]))
        if op == "strsplit":
            return a[0].strsplit(str(a[1]))
        if op == "countmatches":
            return a[0].countmatches(a[1] if isinstance(a[1], list) else str(a[1]))
        if op == "is.na":
            v = a[0]
            if isinstance(v, (int, float)):
                return Frame.from_dict({"isNA": np.asarray(
                    [float(v != v)])})  # NaN-aware scalar
            return Frame.from_dict(
                {n: c.isna_np().astype(np.float64)
                 for n, c in zip(v.names, v.vecs())})
        raise ValueError(f"Rapids: unknown op {op!r}")
