"""Rapids expression interpreter — the Lisp strings `/99/Rapids` accepts.

Reference parity: `h2o-core/src/main/java/water/rapids/Rapids.java` (the
recursive-descent sexpr parser) + `water/rapids/ast/prims/**` (the prim
table). The h2o-py client compiles every Frame operation into one of these
strings; this module implements the subset the Python surface emits most:
arithmetic/comparison binops, slicing (`cols`/`rows`), `cbind`/`rbind`,
reducers (`mean`/`sum`/`sd`/`min`/`max`), `quantile`, `table`, `merge`,
`asfactor`/`as.numeric`, `ifelse`, `unique`, `assign`/`tmp` naming.

Number/string/list literals follow the reference grammar: `[1 2 3]` numeric
list, `["a" "b"]` string list, `(op arg …)` application, bare tokens are
DKV keys or prim names.
"""

from __future__ import annotations

from typing import Any, List

import numpy as np

import math

from . import rapids as rapids_ops
from .frame import Frame
from .vec import Vec


def _safe_vectorize(fn):
    def apply(x):
        x = np.asarray(x, np.float64)
        out = np.full(x.shape, np.nan)
        it = np.nditer(x, flags=["multi_index"])
        for v in it:
            try:
                out[it.multi_index] = fn(float(v))
            except ValueError:
                pass
            except OverflowError:
                out[it.multi_index] = np.inf
        return out
    return apply


_lgamma = _safe_vectorize(math.lgamma)
_gamma = _safe_vectorize(math.gamma)


def _digamma(x):
    """ψ(x) without scipy: reflection for x<0, recurrence to x≥6, then the
    asymptotic series (Abramowitz & Stegun 6.3.18) — ~1e-12 accurate."""
    x = np.asarray(x, np.float64)
    neg = x < 0.5
    # reflection ψ(1−x) − π/tan(πx) keeps the series region positive
    xr = np.where(neg, 1.0 - x, x)
    res = np.zeros_like(xr)
    for _ in range(9):                       # push into the asymptotic zone
        small = xr < 9
        res -= np.where(small, 1.0 / xr, 0.0)
        xr = xr + small
    inv = 1.0 / xr
    inv2 = inv * inv
    res += (np.log(xr) - 0.5 * inv
            - inv2 * (1 / 12.0 - inv2 * (1 / 120.0 - inv2 * (
                1 / 252.0 - inv2 / 240.0))))
    with np.errstate(all="ignore"):
        res = np.where(neg, res - np.pi / np.tan(np.pi * x), res)
    # poles at non-positive integers
    return np.where((x <= 0) & (x == np.floor(x)), np.nan, res)


def _trigamma(x):
    """ψ′(x): reflection ψ′(1−x) = π²/sin²(πx) − ψ′(x), recurrence, series."""
    x = np.asarray(x, np.float64)
    neg = x < 0.5
    xr = np.where(neg, 1.0 - x, x)
    res = np.zeros_like(xr)
    for _ in range(9):
        small = xr < 9
        res += np.where(small, 1.0 / (xr * xr), 0.0)
        xr = xr + small
    inv = 1.0 / xr
    inv2 = inv * inv
    res += inv * (1.0 + 0.5 * inv
                  + inv2 * (1 / 6.0 - inv2 * (1 / 30.0 - inv2 * (
                      1 / 42.0 - inv2 / 30.0))))
    with np.errstate(all="ignore"):
        refl = (np.pi / np.sin(np.pi * x)) ** 2 - res
        res = np.where(neg, refl, res)
    return np.where((x <= 0) & (x == np.floor(x)), np.nan, res)


def _levenshtein(a: str, b: str) -> float:
    if a == b:
        return 0.0
    la, lb = len(a), len(b)
    if la == 0 or lb == 0:
        return float(max(la, lb))
    prev = list(range(lb + 1))
    for i in range(1, la + 1):
        cur = [i] + [0] * lb
        ca = a[i - 1]
        for j in range(1, lb + 1):
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1,
                         prev[j - 1] + (ca != b[j - 1]))
        prev = cur
    return float(prev[lb])


def _lcs_distance(a: str, b: str) -> float:
    """LongestCommonSubsequenceDistance (commons-text): |a|+|b| − 2·|LCS|."""
    la, lb = len(a), len(b)
    if la == 0 or lb == 0:
        return float(la + lb)
    prev = [0] * (lb + 1)
    for i in range(1, la + 1):
        cur = [0] * (lb + 1)
        ca = a[i - 1]
        for j in range(1, lb + 1):
            cur[j] = (prev[j - 1] + 1 if ca == b[j - 1]
                      else max(prev[j], cur[j - 1]))
        prev = cur
    return float(la + lb - 2 * prev[lb])


def _qgram_distance(a: str, b: str, q: int = 2) -> float:
    """Ukkonen q-gram distance (q=2): Σ_g |count_a(g) − count_b(g)| over
    the union of q-gram profiles; strings shorter than q compare by their
    full text."""
    if a == b:
        return 0.0
    if len(a) < q or len(b) < q:
        return float(max(1, abs(len(a) - len(b))))   # a != b here
    from collections import Counter

    pa = Counter(a[i:i + q] for i in range(len(a) - q + 1))
    pb = Counter(b[i:i + q] for i in range(len(b) - q + 1))
    return float(sum(abs(pa[g] - pb[g]) for g in pa.keys() | pb.keys()))


def _jaccard_distance(a: str, b: str) -> float:
    """Jaccard DISTANCE over character sets (commons-text
    JaccardDistance): 1 − |A∩B| / |A∪B|."""
    sa, sb = set(a), set(b)
    if not sa and not sb:
        return 0.0
    return 1.0 - len(sa & sb) / len(sa | sb)


_SOUNDEX_MAP = {**{c: d for cs, d in (
    ("BFPV", "1"), ("CGJKQSXZ", "2"), ("DT", "3"),
    ("L", "4"), ("MN", "5"), ("R", "6")) for c in cs}}


def _soundex(s: str) -> str:
    """American Soundex code (commons-codec Soundex): letter + 3 digits."""
    letters = [c for c in s.upper() if c.isalpha()]
    if not letters:
        return ""
    out = letters[0]
    last = _SOUNDEX_MAP.get(letters[0], "")
    for c in letters[1:]:
        d = _SOUNDEX_MAP.get(c, "")
        if d and d != last:
            out += d
            if len(out) == 4:
                break
        if c not in "HW":       # H/W are transparent for adjacency
            last = d
    return (out + "000")[:4]


def _soundex_diff(a: str, b: str) -> float:
    """commons-codec `SoundexUtils.difference`: number of agreeing
    positions of the two 4-character codes (0..4)."""
    ca, cb = _soundex(a), _soundex(b)
    if not ca or not cb:
        return 0.0
    return float(sum(x == y for x, y in zip(ca, cb)))


def _jaro_winkler(a: str, b: str) -> float:
    """Jaro-Winkler SIMILARITY in [0,1] (Apache commons-text semantics)."""
    if a == b:
        return 1.0
    la, lb = len(a), len(b)
    if la == 0 or lb == 0:
        return 0.0
    window = max(la, lb) // 2 - 1
    ma = [False] * la
    mb = [False] * lb
    matches = 0
    for i in range(la):
        lo, hi = max(0, i - window), min(lb, i + window + 1)
        for j in range(lo, hi):
            if not mb[j] and a[i] == b[j]:
                ma[i] = mb[j] = True
                matches += 1
                break
    if matches == 0:
        return 0.0
    t = 0
    k = 0
    for i in range(la):
        if ma[i]:
            while not mb[k]:
                k += 1
            if a[i] != b[k]:
                t += 1
            k += 1
    m = float(matches)
    jaro = (m / la + m / lb + (m - t / 2) / m) / 3
    prefix = 0
    for ca, cb in zip(a, b):
        if ca != cb or prefix == 4:
            break
        prefix += 1
    return jaro + prefix * 0.1 * (1 - jaro)


def _moment_rows(vals, rows, tzname: str) -> np.ndarray:
    """The seed per-row `(moment ...)` evaluation over the given row
    indices: one datetime() construction each, invalid components → NaN.
    Also the exact-value fixup the vectorized path uses for timestamps
    beyond float64's exact-integer range (CPython's total_seconds divides
    the exact integer microseconds ONCE; two-step float math can differ
    by an ulp out there)."""
    import datetime as _dt
    import zoneinfo

    tz = zoneinfo.ZoneInfo(tzname)
    rows = list(rows)
    out = np.empty(len(rows), np.float64)
    for k, r in enumerate(rows):
        y_, mo, dy, hr, mi, se, ms = (vals[j][r] for j in range(7))
        try:
            t = _dt.datetime(int(y_), int(mo), int(dy), int(hr),
                             int(mi), int(se), int(ms) * 1000,
                             tzinfo=tz)
            out[k] = t.timestamp() * 1000.0
        except (ValueError, OverflowError):
            out[k] = np.nan
    return out


def _moment_vectorized(vals, nrow: int) -> np.ndarray:
    """UTC `(moment ...)` as datetime64 calendar algebra: truncate the
    seven component columns, range-check them exactly like the datetime
    constructor (day-in-month overflow detected by the month rolling), and
    emit `(total_us / 1e6) * 1000.0` — the same float expression
    `datetime.timestamp() * 1000.0` evaluates, so results are bit-identical
    (rows whose |µs| ≥ 2^53 re-run through `_moment_rows` because CPython
    divides the exact integer there)."""
    comp = np.stack([np.asarray(v, np.float64) for v in vals], axis=0)
    finite = np.isfinite(comp).all(axis=0)
    # clip before the int cast: a finite-but-huge component must fail the
    # range check below, not overflow int64
    ci = np.trunc(np.clip(np.where(finite, comp, 0.0),
                          -1e15, 1e15)).astype(np.int64)
    y, mo, dy, hr, mi, se, ms = ci
    ok = (finite & (y >= 1) & (y <= 9999) & (mo >= 1) & (mo <= 12)
          & (dy >= 1) & (dy <= 31) & (hr >= 0) & (hr <= 23)
          & (mi >= 0) & (mi <= 59) & (se >= 0) & (se <= 59)
          & (ms >= 0) & (ms <= 999))
    out = np.full(nrow, np.nan)
    if ok.any():
        m64 = ((y[ok] - 1970) * 12 + (mo[ok] - 1)).astype("datetime64[M]")
        d64 = m64.astype("datetime64[D]") + (dy[ok] - 1)
        ok_day = d64.astype("datetime64[M]") == m64  # Feb 30 rolls → invalid
        days = d64.astype(np.int64)
        total_us = ((days * 86400 + hr[ok] * 3600 + mi[ok] * 60 + se[ok])
                    * 1_000_000 + ms[ok] * 1000)
        res = (total_us.astype(np.float64) / 1e6) * 1000.0
        res[~ok_day] = np.nan
        big = ok_day & (np.abs(total_us) >= (1 << 53))
        if big.any():
            idx = np.flatnonzero(ok)[big]
            res[big] = _moment_rows(vals, idx.tolist(), "UTC")
        out[ok] = res
    return out


# (setproperty k v) — the reference sets a JVM system property; the analog
# here is a session-scoped property table (readable for parity tests)
_SYS_PROPS: dict = {}
_TIME_ZONE = ["UTC"]  # (getTimeZone)/(setTimeZone tz) mutable holder

# unary elementwise math (ast/prims/math/AstUniOp subclasses) and the
# cumulative family — module-level constants (rebuilt-per-node dicts would
# dominate per-row apply/ddply lambdas). Cumulative ops propagate NA like
# the reference AstCumSum (no nan-skipping).
_UNARY = {
    "abs": np.abs, "sign": np.sign, "sqrt": np.sqrt,
    "exp": np.exp, "expm1": np.expm1, "log": np.log,
    "log2": np.log2, "log10": np.log10, "log1p": np.log1p,
    "floor": np.floor, "ceiling": np.ceil, "trunc": np.trunc,
    "cos": np.cos, "sin": np.sin, "tan": np.tan,
    "acos": np.arccos, "asin": np.arcsin, "atan": np.arctan,
    "cosh": np.cosh, "sinh": np.sinh, "tanh": np.tanh,
    "gamma": _gamma,
    "lgamma": _lgamma,
    "not": lambda x: (~(x.astype(bool))).astype(np.float64),
    "!": lambda x: (~(x.astype(bool))).astype(np.float64),
    "acosh": np.arccosh, "asinh": np.arcsinh, "atanh": np.arctanh,
    "cospi": lambda x: np.cos(np.pi * x),
    "sinpi": lambda x: np.sin(np.pi * x),
    "tanpi": lambda x: np.tan(np.pi * x),
    "digamma": _digamma,
    "trigamma": _trigamma,
}
_CUM = {"cumsum": np.cumsum, "cumprod": np.cumprod,
        "cummin": np.minimum.accumulate, "cummax": np.maximum.accumulate}


# -- tokenizer / parser ------------------------------------------------------
def _tokenize(s: str) -> List[str]:
    out, i, n = [], 0, len(s)
    while i < n:
        c = s[i]
        if c.isspace():
            i += 1
        elif c in "()[]{}":
            out.append(c)
            i += 1
        elif c in "\"'":
            j = i + 1
            while j < n and s[j] != c:
                j += 2 if s[j] == "\\" else 1
            out.append(s[i : j + 1])
            i = j + 1
        else:
            j = i
            while j < n and not s[j].isspace() and s[j] not in "()[]{}":
                j += 1
            out.append(s[i:j])
            i = j
    return out


def _parse(tokens: List[str], pos: int = 0):
    t = tokens[pos]
    if t == "(":
        items = []
        pos += 1
        while tokens[pos] != ")":
            node, pos = _parse(tokens, pos)
            items.append(node)
        return ("call", items), pos + 1
    if t == "[":
        items = []
        pos += 1
        while tokens[pos] != "]":
            node, pos = _parse(tokens, pos)
            items.append(node)
        return ("list", items), pos + 1
    if t == "{":
        # lambda: { arg1 arg2 . body }  (water/rapids/ast/AstFunction)
        params = []
        pos += 1
        while tokens[pos] != ".":
            params.append(tokens[pos])
            pos += 1
        body, pos = _parse(tokens, pos + 1)
        if tokens[pos] != "}":
            raise ValueError("Rapids: malformed lambda (expected '}')")
        return ("lambda", (params, body)), pos + 1
    if t and t[0] in "\"'":
        return ("str", t[1:-1]), pos + 1
    try:
        return ("num", float(t)), pos + 1
    except ValueError:
        return ("sym", t), pos + 1


class _Lambda:
    """A rapids `{ args . body }` function value (AstFunction)."""

    def __init__(self, params, body, session):
        self.params = params
        self.body = body
        self.session = session

    def __call__(self, *args):
        sess = self.session
        saved = {p: sess.dkv.get(p) for p in self.params}
        try:
            for p, v in zip(self.params, args):
                sess.dkv.put(p, v)
            return sess._eval(self.body)
        finally:
            for p, v in saved.items():
                if v is None:
                    sess.dkv.remove(p)
                else:
                    sess.dkv.put(p, v)


class RapidsSession:
    """`water.rapids.Session` — holds temp frames across expressions."""

    def __init__(self, dkv=None):
        if dkv is None:
            from ..runtime.dkv import DKV as dkv
        self.dkv = dkv

    # -- evaluation ----------------------------------------------------------
    def execute(self, expr: str):
        """Evaluate a Rapids program: one or MORE top-level sexprs (the
        batch-munging envelope — a remote client ships a whole chain of
        assigns in one POST, `water/rapids/Session` sequential-expression
        semantics). Returns the last statement's value."""
        try:
            tokens = _tokenize(expr)
            asts = []
            pos = 0
            while pos < len(tokens):
                ast, pos = _parse(tokens, pos)
                asts.append(ast)
        except (IndexError, ValueError) as e:
            raise ValueError(
                f"rapids: cannot parse expression {expr[:80]!r}: {e}") from e
        if not asts:
            raise ValueError("rapids: empty program")
        out = None
        for ast in asts:
            out = self._eval(ast)
        return out

    def _eval(self, node) -> Any:
        kind, val = node
        if kind == "num":
            return val
        if kind == "str":
            return val
        if kind == "lambda":
            return _Lambda(val[0], val[1], self)
        if kind == "list":
            return [self._eval(v) for v in val]
        if kind == "sym":
            obj = self.dkv.get(val)
            if obj is not None:
                return obj
            return val  # prim name or bare symbol
        # call
        op = val[0][1] if val[0][0] == "sym" else self._eval(val[0])
        if op in ("assign", "tmp=", "rm") and val[1:] and val[1][0] == "sym":
            # the TARGET key is a literal, never resolved: `(assign rt ...)`
            # must rebind "rt" even when "rt" already names a frame (AstAssign
            # destination-key semantics; evaluating it would store under the
            # old frame's repr and leave the stale binding live)
            args = [val[1][1]] + [self._eval(a) for a in val[2:]]
        else:
            args = [self._eval(a) for a in val[1:]]
        return self._apply(op, args)

    # -- prims ---------------------------------------------------------------
    def _apply(self, op, a: List[Any]):
        """Prim dispatch with a uniform malformed-call guard: wrong arity
        or argument kinds surface as the interpreter's IndexError /
        AttributeError / ZeroDivisionError deep inside a prim — those are
        USER errors (`water/rapids` raises IllegalArgumentException), so
        they map to ValueError → HTTP 400, keeping the detail, instead of
        leaking as 500s (found by fuzzing the `/99/Rapids` surface)."""
        try:
            return self._apply_prim(op, a)
        except (IndexError, AttributeError, ZeroDivisionError) as e:
            raise ValueError(
                f"rapids: malformed call to {op!r} with {len(a)} arg(s): "
                f"{type(e).__name__}: {e}") from e

    def _apply_prim(self, op, a: List[Any]):
        import operator

        if callable(op):
            # a lambda (or other function value) in head position
            return op(*a)

        binops = {
            "+": operator.add, "-": operator.sub, "*": operator.mul,
            "/": operator.truediv, ">": operator.gt, "<": operator.lt,
            ">=": operator.ge, "<=": operator.le, "==": operator.eq,
            "!=": operator.ne,
        }
        if op in binops:
            x, y = a
            if isinstance(x, Frame):
                return binops[op](x, y)
            if isinstance(y, Frame):
                # scalar-first, non-commutative ops must NOT swap operands:
                # (- 5 fr) is 5 − fr. Mirror the frame-first return types
                # (per-column Frame for arithmetic, raw mask for comparisons)
                xv = np.asarray(x, np.float64)
                if op in ("+", "-", "*", "/"):
                    return Frame.from_dict(
                        {n: binops[op](xv, y.vec(n).numeric_np())
                         for n in y.names})
                return binops[op](xv, y._col0().astype(np.float64))
            return binops[op](x, y)
        if op in ("^", "%%", "%/%", "&", "|", "&&", "||"):
            def _val(v):
                return (v._col0().astype(np.float64) if isinstance(v, Frame)
                        else np.asarray(v, np.float64))

            x, y = _val(a[0]), _val(a[1])
            if op == "^":
                out = np.power(x, y)
            elif op == "%%":
                out = np.mod(x, y)
            elif op == "%/%":
                out = np.floor_divide(x, y)
            else:
                # R three-valued logic: NA&FALSE is FALSE, NA|TRUE is TRUE
                nx, ny = np.isnan(x), np.isnan(y)
                tx = np.where(nx, False, x != 0)
                ty = np.where(ny, False, y != 0)
                if op in ("&", "&&"):
                    known_false = (~nx & ~tx) | (~ny & ~ty)
                    out = np.where(known_false, 0.0,
                                   np.where(nx | ny, np.nan, 1.0))
                else:
                    known_true = tx | ty
                    out = np.where(known_true, 1.0,
                                   np.where(nx | ny, np.nan, 0.0))
            if out.ndim == 0:
                return float(out)
            return Frame.from_dict({"C1": out})
        if op in ("assign", "tmp="):
            key, value = a
            if isinstance(value, Frame):
                value.key = str(key)
            self.dkv.put(str(key), value)
            return value
        if op == "rm":
            self.dkv.remove(str(a[0]))
            return None
        if op == "cols":
            fr, sel = a
            names = (
                [fr.names[int(i)] for i in sel]
                if all(isinstance(i, float) for i in sel)
                else [str(s) for s in sel]
            ) if isinstance(sel, list) else (
                [fr.names[int(sel)]] if isinstance(sel, float) else [str(sel)]
            )
            return fr[names]
        if op == "rows":
            fr, sel = a
            if isinstance(sel, Frame):  # boolean mask frame
                mask = sel._col0().astype(bool)
                return fr.take(np.nonzero(mask)[0])
            idx = np.asarray([int(i) for i in (sel if isinstance(sel, list) else [sel])])
            return fr.take(idx)
        if op == "cbind":
            out = a[0]
            for fr in a[1:]:
                out = out.cbind(fr)
            return out
        if op == "rbind":
            out = a[0]
            for fr in a[1:]:
                out = out.rbind(fr)
            return out
        if op in ("mean", "sum", "sd", "min", "max", "median"):
            fr = a[0]
            col = fr._col0() if isinstance(fr, Frame) else np.asarray(fr)
            fn = {"mean": np.nanmean, "sum": np.nansum, "sd": lambda c: np.nanstd(c, ddof=1),
                  "min": np.nanmin, "max": np.nanmax, "median": np.nanmedian}[op]
            return float(fn(col))
        if op == "quantile":
            fr, probs = a[0], a[1]
            return rapids_ops.quantile(fr, [float(p) for p in probs])
        if op == "table":
            return rapids_ops.table(a[0])
        if op == "merge":
            left, right = a[0], a[1]
            all_x = bool(a[2]) if len(a) > 2 else False
            all_y = bool(a[3]) if len(a) > 3 else False
            return rapids_ops.merge(left, right, all_x=all_x, all_y=all_y)
        if op == "as.factor":
            return a[0].asfactor()
        if op == "as.numeric":
            fr = a[0]
            v = fr.vecs()[0]
            return Frame({fr.names[0]: Vec(v.numeric_np(), "real")})
        if op == "unique":
            fr = a[0]
            v = fr.vecs()[0]
            if v.type == "enum":
                vals = sorted(set(np.asarray(v.data)[np.asarray(v.data) >= 0]))
                dom = v.domain
                return Frame.from_dict(
                    {fr.names[0]: np.asarray([dom[i] for i in vals], dtype=object)},
                    column_types={fr.names[0]: "enum"})
            u = np.unique(v.numeric_np())
            return Frame.from_dict({fr.names[0]: u[~np.isnan(u)]})
        if op == "ifelse":
            cond, yes, no = a
            craw = (cond._col0() if isinstance(cond, Frame)
                    else np.asarray(cond, np.float64))
            yv = yes._col0() if isinstance(yes, Frame) else yes
            nv = no._col0() if isinstance(no, Frame) else no
            out = np.where(craw != 0, yv, nv).astype(np.float64)
            # NA condition propagates NA (AstIfElse), not the yes branch
            out[np.isnan(craw)] = np.nan
            return Frame.from_dict({"ifelse": out})
        if op == "nrow":
            return float(a[0].nrow)
        if op == "ncol":
            return float(a[0].ncol)
        if op == "colnames=":
            fr, _idx, names = a
            new = [str(n) for n in names]
            return Frame(dict(zip(new, fr.vecs())))
        if op == "tokenize":
            return a[0].tokenize(str(a[1]))
        def _truthy(v, default=True):
            """Rapids booleans arrive as TRUE/FALSE symbols or 0/1 numbers."""
            if v is None:
                return default
            if isinstance(v, str):
                return v.upper() in ("TRUE", "T", "1")
            if isinstance(v, (int, float)):
                return bool(v)
            raise ValueError(f"Rapids: expected a boolean, got {v!r}")

        if op == "sort":
            fr, sel = a[0], a[1]
            cols = [int(i) for i in (sel if isinstance(sel, list) else [sel])]
            asc = True
            if len(a) > 2:  # ascending flags per key column
                flags = a[2] if isinstance(a[2], list) else [a[2]]
                asc = [_truthy(f) for f in flags]
                if len(asc) == 1:
                    asc = asc[0]
            return fr.sort([fr.names[i] for i in cols], ascending=asc)
        if op == "h2o.impute":
            fr = a[0]
            col = int(a[1]) if len(a) > 1 else None
            method = str(a[2]).lower() if len(a) > 2 else "mean"
            by = None
            if len(a) > 4 and isinstance(a[4], list) and a[4]:
                by = [fr.names[int(i)] for i in a[4]]
            return fr.impute(fr.names[col] if col is not None and col >= 0 else None,
                             method=method, by=by)
        if op == "scale":
            # per-column numeric center/scale lists are a reference feature
            # this subset doesn't implement — reject rather than silently
            # substituting computed statistics
            for v in a[1:3]:
                if isinstance(v, list):
                    raise ValueError("Rapids scale: per-column center/scale "
                                     "lists not supported")
            center = _truthy(a[1] if len(a) > 1 else None)
            sc = _truthy(a[2] if len(a) > 2 else None)
            return a[0].scale(center=center, scale=sc)
        if op == "hist":
            return a[0].hist(int(a[1]) if len(a) > 1 else 20)
        if op == "cut":
            return a[0].cut([float(b) for b in a[1]])
        if op in ("year", "month", "day", "hour", "minute", "second",
                  "dayOfWeek"):
            return getattr(a[0], op)()
        if op in ("trim", "tolower", "toupper", "na.omit"):
            meth = {"na.omit": "na_omit"}.get(op, op)
            return getattr(a[0], meth)()
        if op in ("replacefirst", "replaceall"):
            fn = "sub" if op == "replacefirst" else "gsub"
            return getattr(a[0], fn)(str(a[1]), str(a[2]))
        if op == "strsplit":
            return a[0].strsplit(str(a[1]))
        if op == "countmatches":
            return a[0].countmatches(a[1] if isinstance(a[1], list) else str(a[1]))
        if op == "toTitle":
            return a[0]._map_strings(str.title)
        if op == "strDistance":
            # (strDistance x y measure compare_empty) — ast/prims/string/
            # AstStrDistance over Apache commons-text measures; "lv" is the
            # edit count, "jw" the Jaro-Winkler similarity
            measure = str(a[2]).lower() if len(a) > 2 else "lv"
            cmp_empty = _truthy(a[3] if len(a) > 3 else None, default=True)
            fn = {"lv": _levenshtein, "jw": _jaro_winkler,
                  "lcs": _lcs_distance, "qgram": _qgram_distance,
                  "jaccard": _jaccard_distance,
                  "soundex": _soundex_diff}.get(measure)
            if fn is None:
                raise ValueError(
                    f"strDistance measure {measure!r}: expected one of "
                    "lv, lcs, qgram, jaccard, jw, soundex")
            xs = a[0]._string_rows()
            ys = a[1]._string_rows()
            if len(xs) != len(ys):
                raise ValueError(
                    f"strDistance: frames disagree on row count "
                    f"({len(xs)} vs {len(ys)})")
            out = np.asarray([
                np.nan if (sx is None or sy is None
                           or (not cmp_empty and (sx == "" or sy == "")))
                else fn(str(sx), str(sy))
                for sx, sy in zip(xs, ys)], np.float64)
            return Frame.from_dict({"distance": out})
        if op == "num_valid_substrings":
            # (num_valid_substrings x path) — count DISTINCT substrings
            # (length >= 2) of each string present in the line-separated
            # words file (ast/prims/string/AstCountSubstringsWords).
            # Factorized: each UNIQUE string is counted once (scattered
            # back through a lookup) — the dominant win on repetitive
            # columns. Large unique sets additionally split over the
            # ingest-style thread pool; _count is GIL-bound python today,
            # so that mostly buys overlap with other request threads (and
            # the seam where a native counter would slot in).
            from . import munge_stats as _ms

            with open(str(a[1])) as f:
                words = {ln.strip() for ln in f if ln.strip()}

            def _count(s: str) -> float:
                subs = {s[i:j] for i in range(len(s))
                        for j in range(i + 2, len(s) + 1)}
                return float(len(subs & words))

            rows = a[0]._string_rows()
            legacy = _ms.legacy_enabled()
            with _ms.op("num_valid_substrings", len(rows),
                        path="legacy" if legacy else "vectorized"):
                if legacy:
                    out = [np.nan if s is None else _count(str(s))
                           for s in rows]
                else:
                    uniq = sorted({str(s) for s in rows if s is not None})
                    import os as _os

                    nthreads = min(_os.cpu_count() or 1, 8)
                    if len(uniq) >= 64 and nthreads > 1:
                        from concurrent.futures import ThreadPoolExecutor

                        step = -(-len(uniq) // nthreads)
                        chunks = [uniq[k:k + step]
                                  for k in range(0, len(uniq), step)]
                        with ThreadPoolExecutor(len(chunks)) as ex:
                            parts = list(ex.map(
                                lambda ch: [_count(s) for s in ch], chunks))
                        counts = [c for p in parts for c in p]
                    else:
                        counts = [_count(s) for s in uniq]
                    lut = dict(zip(uniq, counts))
                    out = [np.nan if s is None else lut[str(s)]
                           for s in rows]
            return Frame.from_dict(
                {"num_valid_substrings": np.asarray(out, np.float64)})
        if op == "moment":
            # (moment yr mo dy hr mi se ms) — epoch millis in UTC
            # (ast/prims/time/AstMoment); each arg a scalar or a column.
            # Vectorized as datetime64 calendar algebra when the session
            # time zone is UTC; per-row datetime construction otherwise
            # (DST arithmetic) and for the seed comparator.
            from . import munge_stats as _ms

            if len(a) != 7:
                raise ValueError(
                    "moment expects 7 args (yr mo dy hr mi se ms), got %d"
                    % len(a))
            cols = [(np.asarray(v._col0()) if isinstance(v, Frame)
                     else None) for v in a[:7]]
            lens = {len(c) for c in cols if c is not None}
            if len(lens) > 1:
                raise ValueError(
                    "moment column args must have equal lengths, got %s"
                    % sorted(lens))
            nrow = max((len(c) for c in cols if c is not None), default=1)
            vals = [(c if c is not None
                     else np.full(nrow, float(a[i])))
                    for i, c in enumerate(cols)]
            legacy = _ms.legacy_enabled()
            per_row = legacy or _TIME_ZONE[0] != "UTC"
            # "legacy" is reserved for the env-forced comparator; the
            # non-UTC per-row route books as "fallback"
            path = ("legacy" if legacy
                    else "fallback" if per_row else "vectorized")
            with _ms.op("moment", nrow, path=path):
                if per_row:
                    out = _moment_rows(vals, range(nrow), _TIME_ZONE[0])
                else:
                    out = _moment_vectorized(vals, nrow)
            return Frame.from_dict({"moment": out})
        if op == "asDate":
            # (asDate col format) — java SimpleDateFormat pattern subset.
            # Factorized: strptime runs once per UNIQUE string (per enum
            # domain label for categoricals) and scatters back through the
            # codes — repeated date strings parse once, not once per row.
            fmt = str(a[1])
            for j, py in (("yyyy", "%Y"), ("yy", "%y"), ("MMM", "%b"),
                          ("MM", "%m"), ("dd", "%d"), ("HH", "%H"),
                          ("mm", "%M"), ("ss", "%S")):
                fmt = fmt.replace(j, py)
            import datetime as _dt
            import zoneinfo

            from . import munge_stats as _ms

            tz = zoneinfo.ZoneInfo(_TIME_ZONE[0])

            def _parse_one(s) -> float:
                try:
                    t = _dt.datetime.strptime(str(s), fmt).replace(tzinfo=tz)
                    return t.timestamp() * 1000.0
                except (ValueError, TypeError):
                    return np.nan

            fr0 = a[0]
            v0 = fr0.vecs()[0]
            legacy = _ms.legacy_enabled()
            per_row = legacy or v0.type not in ("enum", "string")
            path = ("legacy" if legacy
                    else "fallback" if per_row else "vectorized")
            with _ms.op("as_date", fr0.nrow, path=path):
                if per_row:
                    out = np.asarray([_parse_one(s)
                                      for s in fr0._string_rows()],
                                     np.float64)
                elif v0.type == "enum":
                    dom = v0.domain or []
                    parsed = np.asarray([_parse_one(d) for d in dom]
                                        + [np.nan], np.float64)
                    codes = np.asarray(v0.data, np.int64)
                    out = parsed[np.where(codes >= 0, codes, len(dom))]
                else:
                    arr = v0.to_numpy()
                    na = np.asarray(arr == None, bool)  # noqa: E711
                    work = arr.copy()
                    work[na] = ""
                    # unique over the OBJECT array (all-str after the NA
                    # fill): a fixed-width "U" cast would allocate
                    # nrow × max-string-length and one long outlier row
                    # could blow memory
                    uniq, inv = np.unique(work, return_inverse=True)
                    parsed = np.asarray([_parse_one(s)
                                         for s in uniq.tolist()],
                                        np.float64)
                    out = parsed[inv.reshape(-1)]
                    # None rows go through str(None)="None" in the seed
                    # loop — unparseable, so NaN either way
                    out[na] = np.nan
            return Frame({fr0.names[0]: Vec(out, "time")})
        if op == "listTimeZones":
            import zoneinfo

            tz = np.asarray(sorted(zoneinfo.available_timezones()),
                            dtype=object)
            return Frame({"timezones": Vec(None, "string", strings=tz)})
        if op == "getTimeZone":
            return Frame({"tz": Vec(None, "string", strings=np.asarray(
                [_TIME_ZONE[0]], dtype=object))})
        if op == "setTimeZone":
            import zoneinfo

            name = str(a[0])
            if name not in zoneinfo.available_timezones():
                raise ValueError(f"unknown time zone {name!r}")
            _TIME_ZONE[0] = name
            return Frame({"tz": Vec(None, "string", strings=np.asarray(
                [name], dtype=object))})
        if op == "setproperty":
            _SYS_PROPS[str(a[0])] = str(a[1])
            return str(a[1])
        if op == "rank_within_groupby":
            # (rank_within_groupby fr groupby_cols sort_cols ascending
            #  new_name sort_cols_sorted) — row-number rank within each
            # group following the sort order (prims/mungers
            # AstRankWithinGroupBy)
            fr = a[0]
            gcols = [int(i) for i in (a[1] if isinstance(a[1], list) else [a[1]])]
            scols = [int(i) for i in (a[2] if isinstance(a[2], list) else [a[2]])]
            asc = a[3] if len(a) > 3 else []
            asc = [_truthy(f) for f in (asc if isinstance(asc, list) else [asc])]
            if len(asc) < len(scols):
                asc += [True] * (len(scols) - len(asc))
            new_name = str(a[4]) if len(a) > 4 else "New_Rank_column"
            sorted_out = _truthy(a[5] if len(a) > 5 else None, default=False)
            vecs = fr.vecs()
            gdata = [np.asarray(vecs[i].numeric_np()) for i in gcols]
            sdata = [np.asarray(vecs[i].numeric_np()) for i in scols]
            skeys = [(-d if not asc[k] else d) for k, d in enumerate(sdata)]
            order = np.lexsort(tuple(reversed(gdata + skeys)))
            gsorted = np.stack([d[order] for d in gdata], axis=1)
            # NaN == NaN for grouping purposes: NA is its own level (the
            # lexsort already made NA rows contiguous at the end)
            diff = ((gsorted[1:] != gsorted[:-1])
                    & ~(np.isnan(gsorted[1:]) & np.isnan(gsorted[:-1])))
            newgrp = np.r_[True, diff.any(axis=1)]
            pos = np.arange(len(order))
            # groups are contiguous after the lexsort: each row's group
            # start is the latest position flagged as a group head
            gstart = np.maximum.accumulate(np.where(newgrp, pos, 0))
            rank_sorted = pos - gstart + 1
            # NAs in sort columns get NaN rank (reference excludes them)
            na_sorted = np.zeros(len(order), bool)
            for d in sdata:
                na_sorted |= np.isnan(d[order])
            rank_out = np.where(na_sorted, np.nan,
                                rank_sorted.astype(np.float64))
            if sorted_out:
                cols = {n: Vec(np.asarray(v.numeric_np())[order]
                               if v.type != "enum"
                               else np.asarray(v.data)[order],
                               v.type, domain=v.domain)
                        for n, v in zip(fr.names, vecs)}
                cols[new_name] = Vec(rank_out, "real")
                return Frame(cols)
            inv = np.empty_like(order)
            inv[order] = np.arange(len(order))
            cols = dict(zip(fr.names, vecs))
            cols[new_name] = Vec(rank_out[inv], "real")
            return Frame(cols)
        if op == "relevel.by.freq":
            # reorder every enum domain by descending level frequency
            # (prims/mungers AstRelevelByFreq); ties keep lexical order
            fr = a[0]
            topn = int(a[1]) if len(a) > 1 and a[1] is not None else -1
            out = {}
            for n, v in zip(fr.names, fr.vecs()):
                if v.type != "enum" or not v.domain:
                    out[n] = v
                    continue
                codes = np.asarray(v.data)
                counts = np.bincount(codes[codes >= 0],
                                     minlength=len(v.domain))
                order = np.argsort(-counts, kind="stable")
                if topn > 0:
                    # only the topn most frequent move to the front
                    moved = order[:topn]
                    rest = np.asarray(
                        [i for i in range(len(v.domain)) if i not in set(moved.tolist())],
                        np.int64)
                    order = np.concatenate([moved, rest])
                remap = np.empty(len(v.domain), np.int64)
                remap[order] = np.arange(len(v.domain))
                new_codes = np.where(codes >= 0, remap[np.maximum(codes, 0)],
                                     codes)
                out[n] = Vec(new_codes, "enum",
                             domain=[v.domain[i] for i in order])
            return Frame(out)
        if op == "distance":
            # (distance references queries measure) — pairwise row distance,
            # result references.nrow × queries.nrow (prims/advmath
            # AstDistance measures l1/l2/cosine/cosine_sq)
            X = a[0].to_numpy().astype(np.float64)
            Y = a[1].to_numpy().astype(np.float64)
            measure = str(a[2]).lower() if len(a) > 2 else "l2"
            if measure == "l1":
                # chunk the broadcast over the query side: peak memory is
                # O(R · chunk · cols), never R·Q·cols
                qc = max(1, (1 << 24) // max(X.shape[0] * X.shape[1], 1))
                parts = [np.abs(X[:, None, :] - Y[None, j:j + qc, :]
                                ).sum(axis=2)
                         for j in range(0, Y.shape[0], qc)]
                D = np.concatenate(parts, axis=1)
            elif measure == "l2":
                # |x−y|² = |x|² + |y|² − 2x·y — O(R·Q) via one matmul
                sq = (X * X).sum(axis=1)[:, None] + (Y * Y).sum(axis=1)[None]
                D = np.sqrt(np.maximum(sq - 2.0 * (X @ Y.T), 0.0))
            elif measure in ("cosine", "cosine_sq"):
                nx = np.linalg.norm(X, axis=1, keepdims=True)
                ny = np.linalg.norm(Y, axis=1, keepdims=True)
                C = (X @ Y.T) / np.maximum(nx * ny.T, 1e-300)
                D = C * C if measure == "cosine_sq" else C
            else:
                raise ValueError(f"distance measure {measure!r}: expected "
                                 "l1/l2/cosine/cosine_sq")
            return Frame.from_dict({f"C{j+1}": D[:, j]
                                    for j in range(D.shape[1])})
        if op == "isax":
            # (isax fr num_words max_cardinality optimize_card) — per-row
            # z-normalized PAA then SAX discretization; the iSAX word joins
            # symbol ids with '^' (prims/timeseries AstIsax)
            fr = a[0]
            nw = int(a[1])
            card = int(a[2]) if len(a) > 2 else 8
            X = fr.to_numpy().astype(np.float64)
            mu = np.nanmean(X, axis=1, keepdims=True)
            sd = np.nanstd(X, axis=1, keepdims=True)
            Z = (X - mu) / np.where(sd > 0, sd, 1.0)
            # PAA: split each row into nw near-equal segments
            idx = np.linspace(0, X.shape[1], nw + 1).astype(int)
            paa = np.stack([Z[:, idx[i]:max(idx[i + 1], idx[i] + 1)].mean(axis=1)
                            for i in range(nw)], axis=1)
            # gaussian breakpoints for `card` symbols
            from statistics import NormalDist

            bps = np.asarray([NormalDist().inv_cdf(q) for q in
                              np.linspace(0, 1, card + 1)[1:-1]])
            sym = np.searchsorted(bps, paa)
            words = np.asarray(["^".join(str(int(s)) for s in row)
                                for row in sym], dtype=object)
            out = {"iSax_index": Vec(None, "string", strings=words)}
            for i in range(nw):
                out[f"iSax_word_{i}"] = Vec(sym[:, i].astype(np.float64),
                                            "real")
            return Frame(out)
        if op == "setLevel":
            # (setLevel col level) — every row becomes `level`
            fr = a[0]
            v = fr.vecs()[0]
            if v.type != "enum":
                raise ValueError("setLevel requires a categorical column")
            lvl = str(a[1])
            if lvl not in (v.domain or []):
                raise ValueError(f"setLevel: {lvl!r} not in domain")
            code = v.domain.index(lvl)
            return Frame({fr.names[0]: Vec(
                np.full(fr.nrow, code, np.int64), "enum", domain=v.domain)})
        if op == "append":
            # (append fr value name) — add a column (prims/mungers AstAppend)
            fr, val, name = a[0], a[1], str(a[2])
            cols = dict(zip(fr.names, fr.vecs()))
            if isinstance(val, Frame):
                cols[name] = val.vecs()[0]
            else:
                cols[name] = Vec(np.full(fr.nrow, float(val), np.float64),
                                 "real")
            return Frame(cols)
        if op == "is.na":
            v = a[0]
            if isinstance(v, (int, float)):
                return Frame.from_dict({"isNA": np.asarray(
                    [float(v != v)])})  # NaN-aware scalar
            return Frame.from_dict(
                {n: c.isna_np().astype(np.float64)
                 for n, c in zip(v.names, v.vecs())})

        if op in _UNARY:
            fn = _UNARY[op]
            v = a[0]
            if isinstance(v, (int, float)):
                return float(fn(np.asarray(v, np.float64)))
            return Frame({n: Vec(fn(c.numeric_np()).astype(np.float64), "real")
                          for n, c in zip(v.names, v.vecs())})
        if op == "round":
            digits = int(a[1]) if len(a) > 1 else 0
            v = a[0]
            return Frame({n: Vec(np.round(c.numeric_np(), digits), "real")
                          for n, c in zip(v.names, v.vecs())})
        if op == "signif":
            digits = int(a[1]) if len(a) > 1 else 6
            v = a[0]

            def sig(c):
                with np.errstate(all="ignore"):
                    mag = np.floor(np.log10(np.abs(c)))
                    f = 10.0 ** (digits - 1 - mag)
                    out = np.round(c * f) / f
                return np.where(np.isfinite(c) & (c != 0), out, c)

            return Frame({n: Vec(sig(c.numeric_np()), "real")
                          for n, c in zip(v.names, v.vecs())})

        # ---- cumulative / reducers ----------------------------------------
        if op in _CUM:
            v = a[0]
            return Frame({n: Vec(_CUM[op](c.numeric_np()).astype(np.float64), "real")
                          for n, c in zip(v.names, v.vecs())})
        if op == "var":
            c = a[0]._col0()
            return float(np.nanvar(c, ddof=1))
        if op == "cor":
            x, y = a[0], a[1]
            return float(np.corrcoef(x._col0(), y._col0())[0, 1])
        if op in ("any", "all"):
            c = (a[0]._col0() if isinstance(a[0], Frame)
                 else np.asarray(a[0], np.float64))
            c = c[~np.isnan(c)]
            return float(getattr(np, op)(c != 0))
        if op in ("any.na", "anyNA"):
            return float(any(v.isna_np().any() for v in a[0].vecs()))
        if op in ("which.max", "which.min"):
            c = a[0]._col0()
            f = np.nanargmax if op == "which.max" else np.nanargmin
            return Frame.from_dict({op: np.asarray([float(f(c))])})
        if op == "which":
            c = (a[0]._col0() if isinstance(a[0], Frame)
                 else np.asarray(a[0], np.float64))
            return Frame.from_dict({"which": np.nonzero(c != 0)[0].astype(np.float64)})
        if op == "prod":
            return float(np.nanprod(a[0]._col0()))

        # ---- predicates / levels ------------------------------------------
        if op in ("is.factor", "isfactor"):
            return float(all(v.type == "enum" for v in a[0].vecs()))
        if op in ("is.numeric",):
            return float(all(v.type in ("int", "real") for v in a[0].vecs()))
        if op in ("is.character",):
            return float(all(v.type == "string" for v in a[0].vecs()))
        if op == "levels":
            v = a[0].vecs()[0]
            dom = v.domain or []
            return Frame.from_dict({"levels": np.asarray(dom, dtype=object)},
                                   column_types={"levels": "enum"})
        if op == "nlevels":
            return float(a[0].vecs()[0].nlevels)
        if op == "nchar":
            return a[0].nchar()
        if op == "substring":
            fr = a[0]
            start = int(a[1])
            end = int(a[2]) if len(a) > 2 else None
            return fr.substring(start, end)
        if op == "match":
            fr, table = a[0], a[1]
            v = fr.vecs()[0]
            labels = ([str(t) for t in table] if isinstance(table, list)
                      else [str(table)])
            if v.type == "enum":
                vals = np.asarray(
                    [v.domain[c] if c >= 0 else None for c in np.asarray(v.data)],
                    dtype=object)
            else:
                vals = v.numeric_np().astype(object)
            lut = {lbl: i + 1 for i, lbl in enumerate(labels)}  # R: 1-based
            out = np.asarray([float(lut.get(str(x), np.nan))
                              if x is not None else np.nan for x in vals])
            return Frame.from_dict({"match": out})

        # ---- random / misc -------------------------------------------------
        if op == "h2o.runif":
            fr, seed = a[0], int(a[1]) if len(a) > 1 else -1
            rng = np.random.default_rng(None if seed < 0 else seed)
            return Frame.from_dict({"rnd": rng.random(fr.nrow)})

        # ---- group-by / apply (AstGroup, AstDdply, AstApply) --------------
        if op == "GB":
            fr, by = a[0], a[1]
            by_names = [fr.names[int(i)] for i in by]
            gb = fr.group_by(by_names)
            i = 2
            while i + 2 < len(a) + 1:
                agg = str(a[i])
                coli = int(a[i + 1])
                # a[i+2] is the NA-handling mode ("all"/"rm"/"ignore"),
                # honored by GroupBy (AstGroup.NAHandling semantics)
                namode = str(a[i + 2]) if i + 2 < len(a) else "all"
                col = fr.names[coli]
                fn = {"nrow": "count", "mean": "mean", "sum": "sum",
                      "min": "min", "max": "max", "sdev": "sd", "sd": "sd",
                      "var": "var", "median": "median", "mode": "mode"}.get(agg)
                if fn is None:
                    raise ValueError(f"Rapids GB: unknown aggregate {agg!r}")
                if fn == "count":
                    # keep the referenced column so nrow can honor na="rm"
                    gb._add("count", col, namode)
                else:
                    getattr(gb, fn)(col, na=namode)
                i += 3
            return gb.get_frame()
        if op == "ddply":
            fr, by, fun = a[0], a[1], a[2]
            if isinstance(fun, str):
                # bare prim name as the function (e.g. mean)
                fun = (lambda name: lambda f: self._apply(name, [f]))(fun)
            by_names = [fr.names[int(i)] for i in by]
            cols = [np.asarray(fr.vec(n).data) for n in by_names]
            keys = list(zip(*[c.tolist() for c in cols])) if cols else []
            rows = {}
            for r, k in enumerate(keys):
                rows.setdefault(k, []).append(r)
            out_keys, out_vals = [], []
            for k, idx in sorted(rows.items()):
                sub = fr.take(np.asarray(idx))
                res = fun(sub)
                if isinstance(res, Frame):
                    res = [float(v.numeric_np()[0]) for v in res.vecs()]
                elif not isinstance(res, list):
                    res = [float(res)]
                out_keys.append(k)
                out_vals.append(res)
            d = {}
            for j, n in enumerate(by_names):
                v = fr.vec(n)
                kk = np.asarray([k[j] for k in out_keys])
                d[n] = (np.asarray(
                    [v.domain[int(c)] if c >= 0 else None for c in kk],
                    dtype=object)
                        if v.type == "enum" else kk.astype(np.float64))
            for j in range(len(out_vals[0]) if out_vals else 0):
                d[f"ddply_C{j + 1}"] = np.asarray([r[j] for r in out_vals])
            return Frame.from_dict(
                d, column_types={n: "enum" for n in by_names
                                 if fr.vec(n).type == "enum"})
        if op == "apply":
            fr, margin, fun = a[0], int(a[1]), a[2]
            if isinstance(fun, str):
                fun = (lambda name: lambda f: self._apply(name, [f]))(fun)
            if margin == 2:
                outs = {n: fun(fr[[n]]) for n in fr.names}
                return Frame.from_dict(
                    {n: np.asarray([float(v if not isinstance(v, Frame)
                                          else v._col0()[0])])
                     for n, v in outs.items()})
            # margin=1 delegates to Frame.apply's row path: scalar results
            # become one column, k-value results become k columns (upstream
            # AstApply row semantics), ragged widths raise
            return fr.apply(fun, axis=1)
        out = self._apply_tail(op, a, _truthy)
        if out is not NotImplemented:
            return out
        raise ValueError(f"Rapids: unknown op {op!r}")

    def _apply_tail(self, op, a: List[Any], _truthy):
        """The long tail of `ast/prims/**`: NA-propagating reducers, time
        component/construction prims, string metrics, frame reshapers, fold
        columns, sequences. Returns NotImplemented for unknown ops."""
        # ---- NA-propagating reducers + NA counting ------------------------
        if op in ("maxNA", "minNA", "sumNA"):
            c = a[0]._col0()
            return float({"maxNA": np.max, "minNA": np.min,
                          "sumNA": np.sum}[op](c))
        if op == "nacnt":
            return [float(v.isna_np().sum()) for v in a[0].vecs()]
        if op == "mode":
            c = a[0]._col0()
            c = c[~np.isnan(c)]
            u, cnt = np.unique(c, return_counts=True)
            return float(u[np.argmax(cnt)]) if len(u) else float("nan")
        # ---- time components / construction -------------------------------
        if op == "week":
            ms = a[0]._col0()
            # vectorized ISO week: week of the Thursday in the same ISO week
            di = np.floor_divide(np.where(np.isnan(ms), 0.0, ms), 86400000.0
                                 ).astype(np.int64)          # days since epoch
            wd = ((di + 3) % 7) + 1                           # ISO 1=Mon..7=Sun
            thu = (di + 4 - wd).astype("datetime64[D]")
            ystart = thu.astype("datetime64[Y]").astype("datetime64[D]")
            week = ((thu - ystart).astype(np.int64) // 7) + 1.0
            return Frame.from_dict(
                {"week": np.where(np.isnan(ms), np.nan, week)})
        if op == "millis":
            ms = a[0]._col0()
            return Frame.from_dict({"millis": np.where(
                np.isnan(ms), np.nan, np.mod(ms, 1000.0))})
        if op == "mktime":
            # (mktime year month day hour minute second msec) — month/day
            # 0-based like AstMktime; columns or scalars, broadcast
            import datetime

            parts = []
            nmax = 1
            for v in a:
                col = (v._col0() if isinstance(v, Frame)
                       else np.asarray([float(v)]))
                parts.append(col)
                nmax = max(nmax, len(col))
            if any(len(p) not in (1, nmax) for p in parts):
                raise ValueError("mktime: component columns must share one "
                                 "length (or be scalars)")
            parts = [np.broadcast_to(p, (nmax,)) for p in parts]
            while len(parts) < 7:
                parts.append(np.zeros(nmax))
            out = np.empty(nmax)
            for i in range(nmax):
                row = [p[i] for p in parts[:7]]
                if any(np.isnan(r) for r in row):
                    out[i] = np.nan   # AstMktime: NA component ⇒ NA time
                    continue
                y, mo, d, h, mi, s, msec = (int(r) for r in row)
                dt = datetime.datetime(y, mo + 1, d + 1, h, mi, s,
                                       msec * 1000,
                                       tzinfo=datetime.timezone.utc)
                out[i] = dt.timestamp() * 1000.0
            return Frame.from_dict({"mktime": out})
        # ---- string metrics ------------------------------------------------
        if op in ("lstrip", "rstrip"):
            chars = str(a[1]) if len(a) > 1 else None
            fn = ((lambda s: s.lstrip(chars)) if op == "lstrip"
                  else (lambda s: s.rstrip(chars)))
            return a[0]._map_strings(fn)
        if op == "entropy":
            def ent(s):
                if not s:
                    return 0.0
                _, cnt = np.unique(list(s), return_counts=True)
                p = cnt / cnt.sum()
                return float(-(p * np.log2(p)).sum())

            return self._string_metric(a[0], "entropy", ent)
        if op == "grep":
            import re

            fr, pattern = a[0], str(a[1])
            ignore_case = len(a) > 2 and _truthy(a[2], default=False)
            invert = len(a) > 3 and _truthy(a[3], default=False)
            output_logical = len(a) > 4 and _truthy(a[4], default=False)
            fl = re.IGNORECASE if ignore_case else 0
            hit = self._string_metric(
                fr, "grep",
                lambda s: float(bool(re.search(pattern, s, fl))))._col0()
            if invert:
                hit = 1.0 - hit
            if output_logical:
                return Frame.from_dict({"grep": hit})
            return Frame.from_dict(
                {"grep": np.nonzero(hit > 0)[0].astype(np.float64)})
        # ---- frame introspection / reshapers -------------------------------
        if op in ("colnames", "names"):
            return Frame.from_dict(
                {"names": np.asarray(a[0].names, dtype=object)},
                column_types={"names": "enum"})
        if op == "columnsByType":
            want = str(a[1]).lower() if len(a) > 1 else "numeric"
            sel = {
                "numeric": ("int", "real"),
                "categorical": ("enum",),
                "string": ("string",),
                "time": ("time",),
            }.get(want, ("int", "real"))
            idx = [float(i) for i, n in enumerate(a[0].names)
                   if a[0].vec(n).type in sel]
            return Frame.from_dict({"columns": np.asarray(idx)})
        if op == "filterNACols":
            frac = float(a[1]) if len(a) > 1 else 0.1
            fr = a[0]
            keep = [float(i) for i, n in enumerate(fr.names)
                    if fr.vec(n).isna_np().mean() <= frac]
            return Frame.from_dict({"columns": np.asarray(keep)})
        if op == "flatten":
            fr = a[0]
            v = fr.vecs()[0]
            if v.type in ("enum",):
                c = int(np.asarray(v.data)[0])
                return (v.domain[c] if c >= 0 else None)
            if v.type == "string":
                return v.to_numpy()[0]
            return float(v.numeric_np()[0])
        if op == "getrow":
            fr = a[0]
            if fr.nrow != 1:
                raise ValueError("getrow: frame must have exactly 1 row")
            vals = [float(v.numeric_np()[0]) if v.type != "string" else np.nan
                    for v in fr.vecs()]
            return Frame.from_dict({"getrow": np.asarray(vals)})
        if op == "melt":
            fr = a[0]
            ids = [fr.names[int(i)] for i in (a[1] if isinstance(a[1], list) else [a[1]])]
            vv = (None if len(a) < 3 or a[2] is None or a[2] == []
                  else [fr.names[int(i)] for i in
                        (a[2] if isinstance(a[2], list) else [a[2]])])
            var_name = str(a[3]) if len(a) > 3 else "variable"
            value_name = str(a[4]) if len(a) > 4 else "value"
            skipna = len(a) > 5 and _truthy(a[5], default=False)
            return rapids_ops.melt(fr, ids, vv, var_name, value_name, skipna)
        if op == "pivot":
            fr = a[0]
            return rapids_ops.pivot(fr, str(a[1]), str(a[2]), str(a[3]))
        if op == "relevel":
            fr, level = a[0], str(a[1])
            v = fr.vecs()[0]
            if v.type != "enum" or level not in (v.domain or []):
                raise ValueError(f"relevel: {level!r} is not a level")
            dom = [level] + [d for d in v.domain if d != level]
            remap = np.asarray([dom.index(d) for d in v.domain])
            codes = np.asarray(v.data)
            new = np.where(codes >= 0, remap[np.maximum(codes, 0)], -1)
            return Frame({fr.names[0]: Vec(new.astype(np.int32), "enum",
                                           domain=dom)})
        if op == "setDomain":
            fr, labels = a[0], [str(s) for s in a[1]]
            v = fr.vecs()[0]
            if v.type != "enum":
                raise ValueError("setDomain: column is not categorical")
            if len(labels) != len(v.domain or []):
                raise ValueError(
                    f"setDomain: {len(labels)} labels for "
                    f"{len(v.domain or [])} levels")
            return Frame({fr.names[0]: Vec(np.asarray(v.data), "enum",
                                           domain=labels)})
        if op == "difflag1":
            c = a[0]._col0()
            return Frame.from_dict(
                {"difflag1": np.r_[np.nan, np.diff(c)]})
        if op == "drop_duplicates":
            # (drop_duplicates fr [col idx...] keep) — AstDropDuplicates:
            # rows deduplicated by the key columns, first/last kept
            fr = a[0]
            cols = ([int(i) for i in a[1]]
                    if len(a) > 1 and isinstance(a[1], list) and a[1]
                    else list(range(fr.ncol)))
            keep = str(a[2]) if len(a) > 2 else "first"
            if keep not in ("first", "last"):
                raise ValueError(
                    f"drop_duplicates: keep must be 'first' or 'last', "
                    f"got {keep!r}")
            vecs = fr.vecs()
            key_cols = []
            for i in cols:
                v = vecs[i]
                if v.type == "string":
                    key_cols.append(np.asarray(v.to_numpy(), dtype=object))
                elif v.type == "enum":
                    key_cols.append(np.asarray(v.data, np.int64))
                else:
                    c = v.numeric_np()
                    # NaN must equal NaN for dedup; +0.0 folds -0.0 onto 0.0
                    key_cols.append(np.where(np.isnan(c), np.inf, c) + 0.0)
            if any(k.dtype == object for k in key_cols):
                # string keys: tuple-hash pass (no vectorized row-unique
                # over mixed object dtypes)
                rows = list(zip(*key_cols))
                it = (range(fr.nrow - 1, -1, -1) if keep == "last"
                      else range(fr.nrow))
                seen = set()
                kept = []
                for i in it:
                    t = rows[i]
                    if t not in seen:
                        seen.add(t)
                        kept.append(i)
                take = np.asarray(sorted(kept), np.int64)
            else:
                keys = np.stack(key_cols, axis=1)
                arr = keys if keep == "first" else keys[::-1]
                _, idx = np.unique(arr, axis=0, return_index=True)
                take = idx if keep == "first" else fr.nrow - 1 - idx
                take = np.sort(take)
            return fr.take(take)
        if op == "h2o.fillna":
            fr = a[0]
            method = str(a[1]).lower() if len(a) > 1 else "forward"
            axis = int(a[2]) if len(a) > 2 else 0
            maxlen = int(a[3]) if len(a) > 3 else 1

            def _fill1d(c):
                c = c.copy()
                idx = np.arange(len(c))
                if method == "backward":
                    c = c[::-1]
                last = np.where(~np.isnan(c), idx, -1)
                last = np.maximum.accumulate(last)
                gap = idx - last
                fill = (last >= 0) & np.isnan(c) & (gap <= maxlen)
                c[fill] = c[last[fill]]
                return c[::-1] if method == "backward" else c

            if axis == 1:
                # fill along ROWS (across columns, left→right)
                M = np.column_stack([v.numeric_np() for v in fr.vecs()])
                M = np.apply_along_axis(_fill1d, 1, M)
                return Frame.from_dict(
                    {n2: M[:, j] for j, n2 in enumerate(fr.names)})
            return Frame.from_dict(
                {n2: _fill1d(v.numeric_np())
                 for n2, v in zip(fr.names, fr.vecs())})
        # ---- fold columns / sequences --------------------------------------
        if op == "kfold_column":
            fr, nfolds = a[0], int(a[1])
            seed = int(a[2]) if len(a) > 2 else -1
            rng = np.random.default_rng(None if seed < 0 else seed)
            return Frame.from_dict(
                {"fold": rng.integers(0, nfolds, fr.nrow).astype(np.float64)})
        if op == "modulo_kfold_column":
            fr, nfolds = a[0], int(a[1])
            return Frame.from_dict(
                {"fold": (np.arange(fr.nrow) % nfolds).astype(np.float64)})
        if op == "stratified_kfold_column":
            fr, nfolds = a[0], int(a[1])
            seed = int(a[2]) if len(a) > 2 else -1
            rng = np.random.default_rng(None if seed < 0 else seed)
            y = np.asarray(fr.vecs()[0].data)
            fold = np.zeros(fr.nrow)
            for cls in np.unique(y):
                ridx = np.nonzero(y == cls)[0]
                ridx = rng.permutation(ridx)
                fold[ridx] = np.arange(len(ridx)) % nfolds
            return Frame.from_dict({"fold": fold})
        if op == "seq":
            frm, to = float(a[0]), float(a[1])
            by = float(a[2]) if len(a) > 2 else (1.0 if to >= frm else -1.0)
            return Frame.from_dict(
                {"seq": np.arange(frm, to + by * 0.5, by)})
        if op == "seq_len":
            return Frame.from_dict(
                {"seq_len": np.arange(1, int(a[0]) + 1).astype(np.float64)})
        if op == "rep_len":
            x, length = a[0], int(a[1])
            vals = (x._col0() if isinstance(x, Frame)
                    else np.asarray([float(x)]))
            reps = -(-length // len(vals))
            return Frame.from_dict({"rep_len": np.tile(vals, reps)[:length]})
        if op == "topn":
            fr, coli = a[0], int(a[1])
            pct = float(a[2]) if len(a) > 2 else 10.0
            top = _truthy(a[3], default=True) if len(a) > 3 else True
            c = fr.vec(fr.names[coli]).numeric_np()
            valid = np.nonzero(~np.isnan(c))[0]   # AstTopN skips NAs
            k = max(1, int(round(len(c) * pct / 100.0)))
            k = min(k, len(valid))
            order = valid[np.argsort(c[valid], kind="mergesort")]
            pick = order[-k:][::-1] if top else order[:k]
            return Frame.from_dict({
                "row_idx": pick.astype(np.float64),
                fr.names[coli]: c[pick]})
        if op == "ls":
            return Frame.from_dict(
                {"key": np.asarray(sorted(self.dkv.keys()), dtype=object)},
                column_types={"key": "enum"})
        return NotImplemented

    @staticmethod
    def _string_metric(fr: Frame, name: str, fn) -> Frame:
        """Per-string numeric metric over the first string/enum column."""
        v = fr.vecs()[0]
        if v.type == "string":
            vals = [None if s is None else fn(str(s)) for s in v.to_numpy()]
            return Frame.from_dict({name: np.asarray(
                [np.nan if x is None else x for x in vals])})
        if v.type == "enum":
            per_level = [fn(str(d)) for d in (v.domain or [])]
            codes = np.asarray(v.data)
            out = np.asarray([per_level[c] if c >= 0 else np.nan
                              for c in codes])
            return Frame.from_dict({name: out})
        raise ValueError(f"{name}: column is not string/categorical")
