"""Rapids expression interpreter — the Lisp strings `/99/Rapids` accepts.

Reference parity: `h2o-core/src/main/java/water/rapids/Rapids.java` (the
recursive-descent sexpr parser) + `water/rapids/ast/prims/**` (the prim
table). The h2o-py client compiles every Frame operation into one of these
strings; this module implements the subset the Python surface emits most:
arithmetic/comparison binops, slicing (`cols`/`rows`), `cbind`/`rbind`,
reducers (`mean`/`sum`/`sd`/`min`/`max`), `quantile`, `table`, `merge`,
`asfactor`/`as.numeric`, `ifelse`, `unique`, `assign`/`tmp` naming.

Number/string/list literals follow the reference grammar: `[1 2 3]` numeric
list, `["a" "b"]` string list, `(op arg …)` application, bare tokens are
DKV keys or prim names.
"""

from __future__ import annotations

from typing import Any, List

import numpy as np

import math

from . import rapids as rapids_ops
from .frame import Frame
from .vec import Vec


def _safe_vectorize(fn):
    def apply(x):
        x = np.asarray(x, np.float64)
        out = np.full(x.shape, np.nan)
        it = np.nditer(x, flags=["multi_index"])
        for v in it:
            try:
                out[it.multi_index] = fn(float(v))
            except ValueError:
                pass
            except OverflowError:
                out[it.multi_index] = np.inf
        return out
    return apply


_lgamma = _safe_vectorize(math.lgamma)
_gamma = _safe_vectorize(math.gamma)

# unary elementwise math (ast/prims/math/AstUniOp subclasses) and the
# cumulative family — module-level constants (rebuilt-per-node dicts would
# dominate per-row apply/ddply lambdas). Cumulative ops propagate NA like
# the reference AstCumSum (no nan-skipping).
_UNARY = {
    "abs": np.abs, "sign": np.sign, "sqrt": np.sqrt,
    "exp": np.exp, "expm1": np.expm1, "log": np.log,
    "log2": np.log2, "log10": np.log10, "log1p": np.log1p,
    "floor": np.floor, "ceiling": np.ceil, "trunc": np.trunc,
    "cos": np.cos, "sin": np.sin, "tan": np.tan,
    "acos": np.arccos, "asin": np.arcsin, "atan": np.arctan,
    "cosh": np.cosh, "sinh": np.sinh, "tanh": np.tanh,
    "gamma": _gamma,
    "lgamma": _lgamma,
    "not": lambda x: (~(x.astype(bool))).astype(np.float64),
    "!": lambda x: (~(x.astype(bool))).astype(np.float64),
}
_CUM = {"cumsum": np.cumsum, "cumprod": np.cumprod,
        "cummin": np.minimum.accumulate, "cummax": np.maximum.accumulate}


# -- tokenizer / parser ------------------------------------------------------
def _tokenize(s: str) -> List[str]:
    out, i, n = [], 0, len(s)
    while i < n:
        c = s[i]
        if c.isspace():
            i += 1
        elif c in "()[]{}":
            out.append(c)
            i += 1
        elif c in "\"'":
            j = i + 1
            while j < n and s[j] != c:
                j += 2 if s[j] == "\\" else 1
            out.append(s[i : j + 1])
            i = j + 1
        else:
            j = i
            while j < n and not s[j].isspace() and s[j] not in "()[]{}":
                j += 1
            out.append(s[i:j])
            i = j
    return out


def _parse(tokens: List[str], pos: int = 0):
    t = tokens[pos]
    if t == "(":
        items = []
        pos += 1
        while tokens[pos] != ")":
            node, pos = _parse(tokens, pos)
            items.append(node)
        return ("call", items), pos + 1
    if t == "[":
        items = []
        pos += 1
        while tokens[pos] != "]":
            node, pos = _parse(tokens, pos)
            items.append(node)
        return ("list", items), pos + 1
    if t == "{":
        # lambda: { arg1 arg2 . body }  (water/rapids/ast/AstFunction)
        params = []
        pos += 1
        while tokens[pos] != ".":
            params.append(tokens[pos])
            pos += 1
        body, pos = _parse(tokens, pos + 1)
        if tokens[pos] != "}":
            raise ValueError("Rapids: malformed lambda (expected '}')")
        return ("lambda", (params, body)), pos + 1
    if t and t[0] in "\"'":
        return ("str", t[1:-1]), pos + 1
    try:
        return ("num", float(t)), pos + 1
    except ValueError:
        return ("sym", t), pos + 1


class _Lambda:
    """A rapids `{ args . body }` function value (AstFunction)."""

    def __init__(self, params, body, session):
        self.params = params
        self.body = body
        self.session = session

    def __call__(self, *args):
        sess = self.session
        saved = {p: sess.dkv.get(p) for p in self.params}
        try:
            for p, v in zip(self.params, args):
                sess.dkv.put(p, v)
            return sess._eval(self.body)
        finally:
            for p, v in saved.items():
                if v is None:
                    sess.dkv.remove(p)
                else:
                    sess.dkv.put(p, v)


class RapidsSession:
    """`water.rapids.Session` — holds temp frames across expressions."""

    def __init__(self, dkv=None):
        if dkv is None:
            from ..runtime.dkv import DKV as dkv
        self.dkv = dkv

    # -- evaluation ----------------------------------------------------------
    def execute(self, expr: str):
        ast, pos = _parse(_tokenize(expr))
        return self._eval(ast)

    def _eval(self, node) -> Any:
        kind, val = node
        if kind == "num":
            return val
        if kind == "str":
            return val
        if kind == "lambda":
            return _Lambda(val[0], val[1], self)
        if kind == "list":
            return [self._eval(v) for v in val]
        if kind == "sym":
            obj = self.dkv.get(val)
            if obj is not None:
                return obj
            return val  # prim name or bare symbol
        # call
        op = val[0][1] if val[0][0] == "sym" else self._eval(val[0])
        args = [self._eval(a) for a in val[1:]]
        return self._apply(op, args)

    # -- prims ---------------------------------------------------------------
    def _apply(self, op, a: List[Any]):
        import operator

        if callable(op):
            # a lambda (or other function value) in head position
            return op(*a)

        binops = {
            "+": operator.add, "-": operator.sub, "*": operator.mul,
            "/": operator.truediv, ">": operator.gt, "<": operator.lt,
            ">=": operator.ge, "<=": operator.le, "==": operator.eq,
            "!=": operator.ne,
        }
        if op in binops:
            x, y = a
            if isinstance(x, Frame) or isinstance(y, Frame):
                return binops[op](x, y) if isinstance(x, Frame) else binops[op](y, x)
            return binops[op](x, y)
        if op in ("assign", "tmp="):
            key, value = a
            if isinstance(value, Frame):
                value.key = str(key)
            self.dkv.put(str(key), value)
            return value
        if op == "rm":
            self.dkv.remove(str(a[0]))
            return None
        if op == "cols":
            fr, sel = a
            names = (
                [fr.names[int(i)] for i in sel]
                if all(isinstance(i, float) for i in sel)
                else [str(s) for s in sel]
            ) if isinstance(sel, list) else (
                [fr.names[int(sel)]] if isinstance(sel, float) else [str(sel)]
            )
            return fr[names]
        if op == "rows":
            fr, sel = a
            if isinstance(sel, Frame):  # boolean mask frame
                mask = sel._col0().astype(bool)
                return fr.take(np.nonzero(mask)[0])
            idx = np.asarray([int(i) for i in (sel if isinstance(sel, list) else [sel])])
            return fr.take(idx)
        if op == "cbind":
            out = a[0]
            for fr in a[1:]:
                out = out.cbind(fr)
            return out
        if op == "rbind":
            out = a[0]
            for fr in a[1:]:
                out = out.rbind(fr)
            return out
        if op in ("mean", "sum", "sd", "min", "max", "median"):
            fr = a[0]
            col = fr._col0() if isinstance(fr, Frame) else np.asarray(fr)
            fn = {"mean": np.nanmean, "sum": np.nansum, "sd": lambda c: np.nanstd(c, ddof=1),
                  "min": np.nanmin, "max": np.nanmax, "median": np.nanmedian}[op]
            return float(fn(col))
        if op == "quantile":
            fr, probs = a[0], a[1]
            return rapids_ops.quantile(fr, [float(p) for p in probs])
        if op == "table":
            return rapids_ops.table(a[0])
        if op == "merge":
            left, right = a[0], a[1]
            all_x = bool(a[2]) if len(a) > 2 else False
            all_y = bool(a[3]) if len(a) > 3 else False
            return rapids_ops.merge(left, right, all_x=all_x, all_y=all_y)
        if op == "as.factor":
            return a[0].asfactor()
        if op == "as.numeric":
            fr = a[0]
            v = fr.vecs()[0]
            return Frame({fr.names[0]: Vec(v.numeric_np(), "real")})
        if op == "unique":
            fr = a[0]
            v = fr.vecs()[0]
            if v.type == "enum":
                vals = sorted(set(np.asarray(v.data)[np.asarray(v.data) >= 0]))
                dom = v.domain
                return Frame.from_dict(
                    {fr.names[0]: np.asarray([dom[i] for i in vals], dtype=object)},
                    column_types={fr.names[0]: "enum"})
            u = np.unique(v.numeric_np())
            return Frame.from_dict({fr.names[0]: u[~np.isnan(u)]})
        if op == "ifelse":
            cond, yes, no = a
            c = cond._col0().astype(bool) if isinstance(cond, Frame) else np.asarray(cond, bool)
            yv = yes._col0() if isinstance(yes, Frame) else yes
            nv = no._col0() if isinstance(no, Frame) else no
            return Frame.from_dict({"ifelse": np.where(c, yv, nv)})
        if op == "nrow":
            return float(a[0].nrow)
        if op == "ncol":
            return float(a[0].ncol)
        if op == "colnames=":
            fr, _idx, names = a
            new = [str(n) for n in names]
            return Frame(dict(zip(new, fr.vecs())))
        if op == "tokenize":
            return a[0].tokenize(str(a[1]))
        def _truthy(v, default=True):
            """Rapids booleans arrive as TRUE/FALSE symbols or 0/1 numbers."""
            if v is None:
                return default
            if isinstance(v, str):
                return v.upper() in ("TRUE", "T", "1")
            if isinstance(v, (int, float)):
                return bool(v)
            raise ValueError(f"Rapids: expected a boolean, got {v!r}")

        if op == "sort":
            fr, sel = a[0], a[1]
            cols = [int(i) for i in (sel if isinstance(sel, list) else [sel])]
            asc = True
            if len(a) > 2:  # ascending flags per key column
                flags = a[2] if isinstance(a[2], list) else [a[2]]
                asc = [_truthy(f) for f in flags]
                if len(asc) == 1:
                    asc = asc[0]
            return fr.sort([fr.names[i] for i in cols], ascending=asc)
        if op == "h2o.impute":
            fr = a[0]
            col = int(a[1]) if len(a) > 1 else None
            method = str(a[2]).lower() if len(a) > 2 else "mean"
            by = None
            if len(a) > 4 and isinstance(a[4], list) and a[4]:
                by = [fr.names[int(i)] for i in a[4]]
            return fr.impute(fr.names[col] if col is not None and col >= 0 else None,
                             method=method, by=by)
        if op == "scale":
            # per-column numeric center/scale lists are a reference feature
            # this subset doesn't implement — reject rather than silently
            # substituting computed statistics
            for v in a[1:3]:
                if isinstance(v, list):
                    raise ValueError("Rapids scale: per-column center/scale "
                                     "lists not supported")
            center = _truthy(a[1] if len(a) > 1 else None)
            sc = _truthy(a[2] if len(a) > 2 else None)
            return a[0].scale(center=center, scale=sc)
        if op == "hist":
            return a[0].hist(int(a[1]) if len(a) > 1 else 20)
        if op == "cut":
            return a[0].cut([float(b) for b in a[1]])
        if op in ("year", "month", "day", "hour", "minute", "second",
                  "dayOfWeek"):
            return getattr(a[0], op)()
        if op in ("trim", "tolower", "toupper", "na.omit"):
            meth = {"na.omit": "na_omit"}.get(op, op)
            return getattr(a[0], meth)()
        if op in ("replacefirst", "replaceall"):
            fn = "sub" if op == "replacefirst" else "gsub"
            return getattr(a[0], fn)(str(a[1]), str(a[2]))
        if op == "strsplit":
            return a[0].strsplit(str(a[1]))
        if op == "countmatches":
            return a[0].countmatches(a[1] if isinstance(a[1], list) else str(a[1]))
        if op == "is.na":
            v = a[0]
            if isinstance(v, (int, float)):
                return Frame.from_dict({"isNA": np.asarray(
                    [float(v != v)])})  # NaN-aware scalar
            return Frame.from_dict(
                {n: c.isna_np().astype(np.float64)
                 for n, c in zip(v.names, v.vecs())})

        if op in _UNARY:
            fn = _UNARY[op]
            v = a[0]
            if isinstance(v, (int, float)):
                return float(fn(np.asarray(v, np.float64)))
            return Frame({n: Vec(fn(c.numeric_np()).astype(np.float64), "real")
                          for n, c in zip(v.names, v.vecs())})
        if op == "round":
            digits = int(a[1]) if len(a) > 1 else 0
            v = a[0]
            return Frame({n: Vec(np.round(c.numeric_np(), digits), "real")
                          for n, c in zip(v.names, v.vecs())})
        if op == "signif":
            digits = int(a[1]) if len(a) > 1 else 6
            v = a[0]

            def sig(c):
                with np.errstate(all="ignore"):
                    mag = np.floor(np.log10(np.abs(c)))
                    f = 10.0 ** (digits - 1 - mag)
                    out = np.round(c * f) / f
                return np.where(np.isfinite(c) & (c != 0), out, c)

            return Frame({n: Vec(sig(c.numeric_np()), "real")
                          for n, c in zip(v.names, v.vecs())})

        # ---- cumulative / reducers ----------------------------------------
        if op in _CUM:
            v = a[0]
            return Frame({n: Vec(_CUM[op](c.numeric_np()).astype(np.float64), "real")
                          for n, c in zip(v.names, v.vecs())})
        if op == "var":
            c = a[0]._col0()
            return float(np.nanvar(c, ddof=1))
        if op == "cor":
            x, y = a[0], a[1]
            return float(np.corrcoef(x._col0(), y._col0())[0, 1])
        if op in ("any", "all"):
            c = (a[0]._col0() if isinstance(a[0], Frame)
                 else np.asarray(a[0], np.float64))
            c = c[~np.isnan(c)]
            return float(getattr(np, op)(c != 0))
        if op in ("any.na", "anyNA"):
            return float(any(v.isna_np().any() for v in a[0].vecs()))
        if op in ("which.max", "which.min"):
            c = a[0]._col0()
            f = np.nanargmax if op == "which.max" else np.nanargmin
            return Frame.from_dict({op: np.asarray([float(f(c))])})
        if op == "which":
            c = (a[0]._col0() if isinstance(a[0], Frame)
                 else np.asarray(a[0], np.float64))
            return Frame.from_dict({"which": np.nonzero(c != 0)[0].astype(np.float64)})
        if op == "prod":
            return float(np.nanprod(a[0]._col0()))

        # ---- predicates / levels ------------------------------------------
        if op in ("is.factor", "isfactor"):
            return float(all(v.type == "enum" for v in a[0].vecs()))
        if op in ("is.numeric",):
            return float(all(v.type in ("int", "real") for v in a[0].vecs()))
        if op in ("is.character",):
            return float(all(v.type == "string" for v in a[0].vecs()))
        if op == "levels":
            v = a[0].vecs()[0]
            dom = v.domain or []
            return Frame.from_dict({"levels": np.asarray(dom, dtype=object)},
                                   column_types={"levels": "enum"})
        if op == "nlevels":
            return float(a[0].vecs()[0].nlevels)
        if op == "nchar":
            return a[0].nchar()
        if op == "substring":
            fr = a[0]
            start = int(a[1])
            end = int(a[2]) if len(a) > 2 else None
            return fr.substring(start, end)
        if op == "match":
            fr, table = a[0], a[1]
            v = fr.vecs()[0]
            labels = ([str(t) for t in table] if isinstance(table, list)
                      else [str(table)])
            if v.type == "enum":
                vals = np.asarray(
                    [v.domain[c] if c >= 0 else None for c in np.asarray(v.data)],
                    dtype=object)
            else:
                vals = v.numeric_np().astype(object)
            lut = {lbl: i + 1 for i, lbl in enumerate(labels)}  # R: 1-based
            out = np.asarray([float(lut.get(str(x), np.nan))
                              if x is not None else np.nan for x in vals])
            return Frame.from_dict({"match": out})

        # ---- random / misc -------------------------------------------------
        if op == "h2o.runif":
            fr, seed = a[0], int(a[1]) if len(a) > 1 else -1
            rng = np.random.default_rng(None if seed < 0 else seed)
            return Frame.from_dict({"rnd": rng.random(fr.nrow)})

        # ---- group-by / apply (AstGroup, AstDdply, AstApply) --------------
        if op == "GB":
            fr, by = a[0], a[1]
            by_names = [fr.names[int(i)] for i in by]
            gb = fr.group_by(by_names)
            i = 2
            while i + 2 < len(a) + 1:
                agg = str(a[i])
                coli = int(a[i + 1])
                # a[i+2] is the NA-handling mode ("all"/"rm"/"ignore")
                col = fr.names[coli]
                fn = {"nrow": "count", "mean": "mean", "sum": "sum",
                      "min": "min", "max": "max", "sdev": "sd", "sd": "sd",
                      "var": "var", "median": "median", "mode": "mode"}.get(agg)
                if fn is None:
                    raise ValueError(f"Rapids GB: unknown aggregate {agg!r}")
                getattr(gb, fn)(col) if fn != "count" else gb.count()
                i += 3
            return gb.get_frame()
        if op == "ddply":
            fr, by, fun = a[0], a[1], a[2]
            if isinstance(fun, str):
                # bare prim name as the function (e.g. mean)
                fun = (lambda name: lambda f: self._apply(name, [f]))(fun)
            by_names = [fr.names[int(i)] for i in by]
            cols = [np.asarray(fr.vec(n).data) for n in by_names]
            keys = list(zip(*[c.tolist() for c in cols])) if cols else []
            rows = {}
            for r, k in enumerate(keys):
                rows.setdefault(k, []).append(r)
            out_keys, out_vals = [], []
            for k, idx in sorted(rows.items()):
                sub = fr.take(np.asarray(idx))
                res = fun(sub)
                if isinstance(res, Frame):
                    res = [float(v.numeric_np()[0]) for v in res.vecs()]
                elif not isinstance(res, list):
                    res = [float(res)]
                out_keys.append(k)
                out_vals.append(res)
            d = {}
            for j, n in enumerate(by_names):
                v = fr.vec(n)
                kk = np.asarray([k[j] for k in out_keys])
                d[n] = (np.asarray(
                    [v.domain[int(c)] if c >= 0 else None for c in kk],
                    dtype=object)
                        if v.type == "enum" else kk.astype(np.float64))
            for j in range(len(out_vals[0]) if out_vals else 0):
                d[f"ddply_C{j + 1}"] = np.asarray([r[j] for r in out_vals])
            return Frame.from_dict(
                d, column_types={n: "enum" for n in by_names
                                 if fr.vec(n).type == "enum"})
        if op == "apply":
            fr, margin, fun = a[0], int(a[1]), a[2]
            if isinstance(fun, str):
                fun = (lambda name: lambda f: self._apply(name, [f]))(fun)
            if margin == 2:
                outs = {n: fun(fr[[n]]) for n in fr.names}
                return Frame.from_dict(
                    {n: np.asarray([float(v if not isinstance(v, Frame)
                                          else v._col0()[0])])
                     for n, v in outs.items()})
            # margin=1 delegates to Frame.apply's row path: scalar results
            # become one column, k-value results become k columns (upstream
            # AstApply row semantics), ragged widths raise
            return fr.apply(fun, axis=1)
        raise ValueError(f"Rapids: unknown op {op!r}")
