"""Text utilities — tokenize, TF-IDF, Grep.

Reference parity:
* `water/rapids/ast/prims/string/AstTokenize.java` — `frame.tokenize(split)`:
  splits every string column row-wise into tokens, stacked into ONE string
  column with a trailing NA after each original row (the sentence separator
  format `hex/word2vec/Word2Vec` consumes).
* `h2o-algos/src/main/java/hex/tfidf/` (TfIdfPreprocessor, DocumentFrequency-
  Task, TermFrequencyTask) exposed as `h2o.tf_idf()` — returns a frame
  [document_id, token, TF, IDF, TF-IDF].
* `h2o-algos/src/main/java/hex/grep/Grep.java` — regex match over a text
  column; returns matching rows.

Host-side string work (like the reference: tokenization runs on the JVM heap,
not the accelerator); the numeric TF/IDF aggregation is numpy segment math.
"""

from __future__ import annotations

import re
from typing import List, Optional

import numpy as np

from .frame import Frame
from .vec import Vec


def _string_rows(v: Vec) -> List[Optional[str]]:
    if v.type == "string":
        return [None if s is None else str(s) for s in v.to_numpy()]
    if v.type == "enum":
        dom = np.asarray((v.domain or []) + [None], dtype=object)
        return [None if s is None else str(s) for s in dom[np.asarray(v.data)]]
    raise ValueError("expected a string/enum column")


def tokenize(frame: Frame, split: str = " ") -> Frame:
    """`H2OFrame.tokenize` — one output string column; each input row's
    tokens are followed by a NA row (sentence boundary)."""
    pat = re.compile(split)
    cols = [v for v in frame.vecs() if v.type in ("string", "enum")]
    if not cols:
        raise ValueError("tokenize: no string columns in frame")
    out: List[Optional[str]] = []
    rows = [_string_rows(v) for v in cols]
    for i in range(frame.nrow):
        for r in rows:
            s = r[i]
            if s is None:
                continue
            out.extend(t for t in pat.split(s) if t)
        out.append(None)
    return Frame({"C1": Vec(None, "string", strings=np.asarray(out, dtype=object))})


def tf_idf(frame: Frame, document_id_col=0, text_col=1, preprocess: bool = True,
           case_sensitive: bool = True) -> Frame:
    """`h2o.tf_idf` — per-(document, token): TF = term count in doc,
    IDF = log((1+N)/(1+DF)), TF-IDF = TF·IDF."""
    names = frame.names
    did_col = names[document_id_col] if isinstance(document_id_col, int) else document_id_col
    txt_col = names[text_col] if isinstance(text_col, int) else text_col
    doc_ids = frame.vec(did_col).numeric_np().astype(np.int64)
    if preprocess:
        texts = _string_rows(frame.vec(txt_col))
        pairs = []
        for d, s in zip(doc_ids, texts):
            if s is None:
                continue
            for t in s.split():
                pairs.append((d, t if case_sensitive else t.lower()))
    else:
        toks = _string_rows(frame.vec(txt_col))
        pairs = [(d, t if case_sensitive else t.lower())
                 for d, t in zip(doc_ids, toks) if t is not None]
    if not pairs:
        raise ValueError("tf_idf: no tokens")
    docs = np.asarray([p[0] for p in pairs])
    words = np.asarray([p[1] for p in pairs], dtype=object)

    tf = {}
    for d, w in zip(docs, words):
        tf[(d, w)] = tf.get((d, w), 0) + 1
    n_docs = len(np.unique(docs))
    df = {}
    for (d, w) in tf:
        df[w] = df.get(w, 0) + 1
    keys = sorted(tf.keys(), key=lambda k: (k[0], str(k[1])))
    out_doc = np.asarray([k[0] for k in keys], np.float64)
    out_tok = np.asarray([k[1] for k in keys], dtype=object)
    out_tf = np.asarray([tf[k] for k in keys], np.float64)
    out_idf = np.asarray([np.log((1.0 + n_docs) / (1.0 + df[k[1]])) for k in keys])
    return Frame({
        did_col: Vec.from_numpy(out_doc),
        "token": Vec(None, "string", strings=out_tok),
        "TF": Vec.from_numpy(out_tf),
        "IDF": Vec.from_numpy(out_idf),
        "TF_IDF": Vec.from_numpy(out_tf * out_idf),
    })


def grep(frame: Frame, regex: str, invert: bool = False) -> Frame:
    """`hex.grep.Grep` — rows of the (single string column) frame matching
    the regex; returns [row_idx, match] like the reference's match offsets."""
    pat = re.compile(regex)
    v = frame.vecs()[0]
    rows = _string_rows(v)
    idx, matches = [], []
    for i, s in enumerate(rows):
        hit = bool(s is not None and pat.search(s))
        if hit != invert:
            idx.append(i)
            matches.append(s)
    return Frame({
        "row": Vec.from_numpy(np.asarray(idx, np.float64)),
        "match": Vec(None, "string", strings=np.asarray(matches, dtype=object)),
    })
