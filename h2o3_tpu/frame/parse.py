"""Distributed parse — CSV/ARFF/SVMLight ingest into Frames.

Reference parity: `h2o-core/src/main/java/water/parser/ParseDataset.java`
(`MultiFileParseTask` MRTask over byte ranges), `ParseSetup.java` (format /
separator / column-type guessing on a sample), `CsvParser.java`,
`Categorical.java` (two-phase global categorical interning),
`SVMLightParser.java`, `ARFFParser.java`.

TPU-native shape of the same design: each host parses its own byte range of
the file(s) into numpy columns (phase 1, embarrassingly parallel), then
categorical domains are unioned globally and local codes renumbered
(phase 2 — the `Categorical` merge) before the columns are placed into HBM.
Single-process mode degenerates to "one byte range". Inside a process,
phase 1 is itself parallel: the byte range splits into RFC-4180-safe
chunks tokenized concurrently (`frame/chunked.py`), with the native C++
tokenizer (`h2o3_tpu/native/` via ctypes) slotting in per chunk when
built and a vectorized numpy path always available. Stage timings and
throughput counters land in `frame/ingest_stats.py` (surfaced at
/3/Profiler and /3/Ingest/metrics — see docs/ingest.md).
"""

from __future__ import annotations

import io
import os
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from .frame import Frame
from .vec import Vec

_NA_TOKENS = {"", "NA", "na", "N/A", "nan", "NaN", "null", "NULL", "?"}


def _count_unquoted(ln: str, ch: str) -> int:
    """Occurrences of `ch` OUTSIDE double-quoted regions — separator
    guessing must not count a comma inside `"last, first"`."""
    cnt, inq = 0, False
    for c in ln:
        if c == '"':
            inq = not inq
        elif c == ch and not inq:
            cnt += 1
    return cnt


def _split_sample_line(ln: str, sep: str) -> List[str]:
    """Quote-aware split for the setup sample — the tokenizer's own
    dispatch (chunked.split_csv_line), so the column count / type guess
    sees exactly the fields the parse phase will produce."""
    from .chunked import split_csv_line

    return split_csv_line(ln, sep)


def parse_setup(path: str, sample_bytes: int = 1 << 16, sep: Optional[str] = None):
    """Guess separator / header / column types from a sample — the
    `ParseSetup.guessSetup` step."""
    with open(path, "rb") as f:
        raw = f.read(sample_bytes)
    # a short read means the sample IS the whole file — the lone-line
    # header tiebreak below must not fire on a truncated first line of a
    # larger file (it would eat that file's first data row)
    sample_is_whole_file = len(raw) < sample_bytes
    sample = raw.decode("utf-8", errors="replace")
    lines = [ln for ln in sample.splitlines() if ln.strip()][:100]
    if not lines:
        raise ValueError(f"empty file {path}")
    if sep is None:
        counts = {c: _count_unquoted(lines[0], c)
                  for c in [",", "\t", ";", "|", " "]}
        sep = max(counts, key=counts.get)
        if counts[sep] == 0:
            sep = ","
    first = _split_sample_line(lines[0], sep)
    # header iff the first line holds a non-numeric token AND at least one
    # data line follows. Lone-line tiebreak: a single multi-column line
    # whose tokens are ALL non-numeric ("id,name\n") is a header over zero
    # rows — the header-only export case; any numeric token (or a single
    # column) keeps the lone line as DATA (the ISSUE-2 rule).
    header = (len(lines) > 1 and not all(_is_num_or_na(t) for t in first)) \
        or (len(lines) == 1 and sample_is_whole_file and len(first) > 1
            and not any(_is_num_or_na(t) for t in first))
    data_lines = lines[1:] if header else lines
    ncol = len(first)
    # split each sample line ONCE and index columns from the cached parts
    # (was O(lines·ncol²): a re-split of every line inside the column loop)
    parts = [_split_sample_line(ln, sep) for ln in data_lines]
    types = []
    for c in range(ncol):
        col = [p[c].strip() if c < len(p) else "" for p in parts]
        types.append("numeric" if all(_is_num_or_na(t) for t in col)
                     else "enum")
    names = [t.strip().strip('"') for t in first] if header else [f"C{i+1}" for i in range(ncol)]
    return {"sep": sep, "header": header, "names": names, "types": types}


def _is_num_or_na(tok: str) -> bool:
    tok = tok.strip().strip('"')
    if tok in _NA_TOKENS:
        return True
    try:
        float(tok)
        return True
    except ValueError:
        return False


def parse_csv(
    path: str,
    sep: Optional[str] = None,
    header: Optional[bool] = None,
    col_names: Optional[Sequence[str]] = None,
    col_types: Optional[Dict[str, str]] = None,
    nthreads: Optional[int] = None,
    chunk_bytes: Optional[int] = None,
) -> Frame:
    """Parse one CSV file into a Frame: chunked multithreaded phase-1
    tokenize (frame/chunked.py — RFC-4180-safe byte chunks on a thread
    pool, native tokenizer per chunk when built), vectorized column
    coercion, then the phase-2 categorical intern. Per-stage wall-clock is
    recorded under ``ingest_*`` in runtime/phases and in
    frame/ingest_stats (surfaced at /3/Profiler and /3/Ingest/metrics).

    `nthreads`/`chunk_bytes` override the H2O3_PARSE_THREADS /
    H2O3_PARSE_CHUNK_BYTES defaults; chunk count never changes the result
    (pinned bit-identical by tests/test_parse_parallel.py). Setting
    H2O3_INGEST_LEGACY=1 routes through the historical per-line tokenizer
    (the bench.py comparator)."""
    from . import chunked as _chunked
    from . import ingest_stats as _stats

    t_start = time.perf_counter()
    marks: Dict[str, float] = {}
    with _stats.stage(marks, "setup"):
        setup = parse_setup(path, sep=sep)
        if header is None:
            header = setup["header"]
        names = list(col_names) if col_names else setup["names"]
        sep = setup["sep"]

    legacy = os.environ.get("H2O3_INGEST_LEGACY", "") not in ("", "0")
    if legacy:
        from ..native import loader as native_loader  # late; optional .so

        nbytes = os.path.getsize(path)
        info = dict(n_chunks=1, n_threads=1, native=False)
        with _stats.stage(marks, "tokenize"):
            cols = native_loader.tokenize_csv(path, sep, header, len(names))
            if cols is None:
                cols = _tokenize_numpy(path, sep, header, len(names))
            else:
                info["native"] = True
    else:
        with _stats.stage(marks, "read"):
            with open(path, "rb") as f:
                data = f.read()
        nbytes = len(data)
        with _stats.stage(marks, "tokenize"):
            # the native pass is all-or-nothing numeric; when the sample
            # already guessed an enum column, don't scan-and-discard (the
            # gate only affects speed — python numerics match strtod)
            cols, info = _chunked.tokenize_data(
                data, sep, header, len(names),
                nthreads=nthreads, chunk_bytes=chunk_bytes,
                use_native=all(t == "numeric" for t in setup["types"]))

    col_types = col_types or {}
    # tokenizer columns are str by construction (native ones are float64 —
    # _column_to_vec short-circuits on dtype), so the coercers may skip
    # their per-element type scans
    assume_str = not info.get("native", False)

    def _coerce(arg):
        i, name = arg
        t_col = time.perf_counter()
        if legacy:   # the seed's sequential per-element coercion
            v = _legacy_tokens_to_vec(cols[i], col_types.get(name))
        else:
            v = _column_to_vec(cols[i], col_types.get(name),
                               assume_str=assume_str)
        return name, v, time.perf_counter() - t_col

    # columns coerce independently (numpy casts/sorts release the GIL), so
    # they share the tokenize pool's width; collectives don't exist here
    # (the distributed path stays sequential for rank-ordered collectives),
    # and the legacy comparator stays sequential like the seed
    nthr = 1 if legacy else (
        nthreads if nthreads is not None else _chunked.default_nthreads())
    idxs = list(enumerate(names))
    if nthr > 1 and len(idxs) > 1:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=min(nthr, len(idxs))) as ex:
            coerced = list(ex.map(_coerce, idxs))
    else:
        coerced = [_coerce(a) for a in idxs]
    vecs = {}
    for name, v, dt in coerced:
        # numeric/time columns book "coerce"; enum/string book "intern"
        # (the categorical merge) — same decomposition /3/Profiler shows.
        # Under the pool these per-column seconds overlap, so bucket sums
        # may exceed the parse wall-clock.
        bucket = "intern" if v.type in ("enum", "string") else "coerce"
        marks[bucket] = marks.get(bucket, 0.0) + dt
        vecs[name] = v
    with _stats.stage(marks, "place"):
        fr = Frame(vecs, key=os.path.basename(path))
    _stats.record(path, fr.nrow, nbytes, time.perf_counter() - t_start,
                  marks, legacy=legacy, **info)
    return fr


def _split_lines(lines: List[str], sep: str, ncol: int) -> List[np.ndarray]:
    """Shared line splitter for the python tokenize paths (whole-file and
    distributed byte-range) — one place for quoting/strip semantics.
    Lines containing a double quote take the RFC-4180 csv reader (so
    quoted cells may hold the separator — what `frame_to_csv` emits);
    everything else keeps the fast plain split."""
    import csv as _csv

    cols: List[list] = [[] for _ in range(ncol)]
    for ln in lines:
        if '"' in ln:
            # the csv reader dequotes; don't strip OR re-strip quotes —
            # quoting exists precisely to preserve edge whitespace and
            # literal quote characters (numeric conversion downstream
            # tolerates surrounding spaces on the rare mixed lines)
            parts = next(_csv.reader([ln], delimiter=sep))
        else:
            parts = [p.strip().strip('"') for p in ln.split(sep)]
        for c in range(ncol):
            cols[c].append(parts[c] if c < len(parts) else "")
    return [np.asarray(c, dtype=object) for c in cols]


def _tokenize_numpy(path: str, sep: str, header: bool, ncol: int) -> List[np.ndarray]:
    """LEGACY tokenizer: whole-file read + per-line split. The chunked
    pipeline (frame/chunked.py) replaced it as the default; it stays as the
    bit-exact reference the parallel path is pinned against
    (tests/test_parse_parallel.py) and as bench.py's speedup comparator
    (H2O3_INGEST_LEGACY=1)."""
    with open(path, "rb") as f:
        text = f.read().decode("utf-8", errors="replace")
    lines = text.splitlines()
    if header:
        lines = lines[1:]
    lines = [ln for ln in lines if ln.strip()]
    return _split_lines(lines, sep, ncol)


def _legacy_tokens_to_vec(col: np.ndarray, hint: Optional[str]) -> Vec:
    """The SEED coercion (pre-chunked-pipeline): per-element `float()`
    loops and object-array `np.unique` interning. Kept verbatim as the
    other half of the H2O3_INGEST_LEGACY comparator — bench.py measures
    the chunked pipeline against the seed's full tokenize+coerce path, and
    tests/test_parse_parallel.py pins the new path bit-identical to it."""
    from .vec import _all_int, _maybe_f32

    if hint in ("real", "int", "numeric", "float"):
        vals = np.asarray(
            [np.nan if str(v).strip() in _NA_TOKENS else float(v)
             for v in col], dtype=np.float64)
        return Vec(_maybe_f32(vals), "real")
    if hint == "string":
        return Vec(None, "string", strings=col)

    def _intern(values: np.ndarray) -> Vec:
        mask = np.asarray([v in ("", "NA", "na", None) for v in values])
        domain, codes = np.unique(np.asarray(values)[~mask],
                                  return_inverse=True)
        full = np.full(len(values), -1, dtype=np.int32)
        full[~mask] = codes.astype(np.int32)
        return Vec(full, "enum", domain=[str(d) for d in domain])

    if hint in ("enum", "factor", "categorical"):
        return _intern(col.astype(object))
    try:
        as_num = np.asarray(
            [np.nan if v in ("", "NA", "na", "nan", None) else float(v)
             for v in col], dtype=np.float64)
        return Vec(_maybe_f32(as_num),
                   "real" if not _all_int(as_num) else "int")
    except (TypeError, ValueError):
        return _intern(col)


def _column_to_vec(col: np.ndarray, hint: Optional[str],
                   assume_str: bool = False) -> Vec:
    if hint in ("real", "int", "numeric", "float"):
        from .vec import _maybe_f32, bulk_try_numeric

        if col.dtype.kind == "f":
            # native-tokenized column: already float64 with NaN NAs
            vals = np.asarray(col, dtype=np.float64)
        else:
            vals = bulk_try_numeric(col, _NA_TOKENS, strip_tokens=True,
                                    assume_str=assume_str)
        return Vec(_maybe_f32(vals), "real")
    if hint in ("enum", "factor", "categorical"):
        return Vec.from_numpy(col if col.dtype.kind in "US"
                              else col.astype(object), "enum",
                              assume_str=assume_str)
    if hint == "string":
        # the fast tokenizer's bytes columns decode for the string pool
        return Vec(None, "string",
                   strings=col.astype("U") if col.dtype.kind == "S" else col)
    return Vec.from_numpy(col, assume_str=assume_str)


def parse_svmlight(path: str) -> Frame:
    """SVMLight ingest (`water/parser/SVMLightParser.java`): sparse
    label qid? idx:val ... lines → dense Frame (labels in "C1")."""
    rows = []
    max_idx = 0
    labels = []
    qids = []
    with open(path) as f:
        for ln in f:
            ln = ln.split("#")[0].strip()
            if not ln:
                continue
            parts = ln.split()
            labels.append(float(parts[0]))
            feats = {}
            for p in parts[1:]:
                k, v = p.split(":")
                if k == "qid":
                    qids.append(int(v))
                    continue
                feats[int(k)] = float(v)
                max_idx = max(max_idx, int(k))
            rows.append(feats)
    X = np.zeros((len(rows), max_idx), dtype=np.float32)
    for r, feats in enumerate(rows):
        for k, v in feats.items():
            X[r, k - 1] = v
    vecs = {"C1": Vec(np.asarray(labels, np.float32), "real")}
    if qids:
        vecs["qid"] = Vec(np.asarray(qids, np.float32), "int")
    for j in range(max_idx):
        vecs[f"C{j+2}"] = Vec(X[:, j], "real")
    return Frame(vecs, key=os.path.basename(path))


def parse_arff(path: str) -> Frame:
    """ARFF ingest (`water/parser/ARFFParser.java`): @attribute declarations
    drive the column types (numeric/real/integer → numeric, {a,b,c} → enum,
    string/date → string); @data rows parse as CSV. Sparse `{i v, …}` data
    rows are expanded dense."""
    names: List[str] = []
    types: List[str] = []
    domains: List[Optional[List[str]]] = []
    data_lines: List[str] = []
    in_data = False
    with open(path, encoding="utf-8", errors="replace") as f:
        for ln in f:
            ln = ln.strip()
            if not ln or ln.startswith("%"):
                continue
            low = ln.lower()
            if in_data:
                data_lines.append(ln)
            elif low.startswith("@attribute"):
                rest = ln[len("@attribute"):].strip()
                if rest.startswith(("'", '"')):
                    q = rest[0]
                    end = rest.index(q, 1)
                    name, typ = rest[1:end], rest[end + 1:].strip()
                else:
                    parts = rest.split(None, 1)
                    name, typ = parts[0], (parts[1] if len(parts) > 1 else "numeric")
                names.append(name)
                tl = typ.strip()
                if tl.startswith("{"):
                    dom = [t.strip().strip("'\"") for t in tl.strip("{}").split(",")]
                    types.append("enum")
                    domains.append(dom)
                elif tl.lower() in ("numeric", "real", "integer"):
                    types.append("numeric")
                    domains.append(None)
                else:  # string / date / relational
                    types.append("string")
                    domains.append(None)
            elif low.startswith("@data"):
                in_data = True
    def _arff_split(ln: str) -> List[str]:
        """Comma split honouring ARFF's single- OR double-quoted values."""
        out, cur, q = [], [], None
        for ch in ln:
            if q:
                if ch == q:
                    q = None
                else:
                    cur.append(ch)
            elif ch in "'\"":
                q = ch
            elif ch == ",":
                out.append("".join(cur).strip())
                cur = []
            else:
                cur.append(ch)
        out.append("".join(cur).strip())
        return out

    ncol = len(names)
    # ARFF spec: omitted sparse entries are value 0 — numeric 0, or the
    # FIRST nominal value for enum columns
    defaults = [
        (domains[i][0] if types[i] == "enum" and domains[i] else "0")
        for i in range(ncol)
    ]
    cols: List[list] = [[] for _ in range(ncol)]
    for ln in data_lines:
        if ln.startswith("{"):  # sparse row: {idx val, idx val}
            vals = list(defaults)
            for pair in ln.strip("{}").split(","):
                pair = pair.strip()
                if not pair:
                    continue
                i, v = pair.split(None, 1)
                vals[int(i)] = v.strip().strip("'\"")
        else:
            vals = _arff_split(ln)
        for c in range(ncol):
            cols[c].append(vals[c] if c < len(vals) else "")
    vecs = {}
    for i, name in enumerate(names):
        col = np.asarray(cols[i], dtype=object)
        if types[i] == "numeric":
            vecs[name] = _column_to_vec(col, "numeric")
        elif types[i] == "enum":
            dom = domains[i]
            lookup = {d: j for j, d in enumerate(dom)}
            codes = np.asarray([lookup.get(str(v), -1) for v in col], np.int32)
            vecs[name] = Vec(codes, "enum", domain=dom)
        else:
            vecs[name] = Vec(None, "string", strings=col)
    return Frame(vecs, key=os.path.basename(path))


def _arrow_table_to_frame(table, key: Optional[str] = None) -> Frame:
    """Arrow table → Frame. Numerics stay floating (NaN = NA), strings/
    dictionaries become enum vecs built from Arrow's EXPLICIT null mask
    (unlike CSV, '' / 'NA' are legitimate values here), booleans become
    0/1, timestamps become ms-since-epoch 'time' columns (NaT → NaN)."""
    import pyarrow as pa

    vecs: Dict[str, Vec] = {}
    for name, col in zip(table.column_names, table.columns):
        arr = col.combine_chunks() if col.num_chunks != 1 else col.chunk(0)
        pyt = arr.type
        if pa.types.is_dictionary(pyt):
            arr = arr.dictionary_decode()
            pyt = arr.type
        if pa.types.is_string(pyt) or pa.types.is_large_string(pyt):
            vals = arr.to_numpy(zero_copy_only=False)   # object, None=null
            valid = np.asarray([v is not None for v in vals])
            uniq = sorted({str(v) for v in vals[valid]})
            lut = {lbl: i for i, lbl in enumerate(uniq)}
            codes = np.asarray(
                [lut[str(v)] if ok else -1 for v, ok in zip(vals, valid)],
                np.int32)
            vecs[name] = Vec(codes, "enum", domain=uniq)
        elif pa.types.is_boolean(pyt):
            vals = arr.to_numpy(zero_copy_only=False)
            vecs[name] = Vec(np.asarray(
                [np.nan if v is None else float(v) for v in vals],
                np.float32), "int")
        elif pa.types.is_timestamp(pyt) or pa.types.is_date(pyt):
            v = arr.cast(pa.timestamp("ms")).to_numpy(zero_copy_only=False)
            nat = np.isnat(v)
            out = v.astype("datetime64[ms]").astype(np.float64)
            out[nat] = np.nan
            vecs[name] = Vec(out, "time")
        elif (pa.types.is_integer(pyt) or pa.types.is_floating(pyt)
              or pa.types.is_decimal(pyt)):
            np_col = arr.to_numpy(zero_copy_only=False).astype(np.float64)
            vecs[name] = Vec.from_numpy(np_col)
        else:
            raise ValueError(
                f"unsupported Arrow column type {pyt} in column {name!r} "
                "(binary/list/struct columns have no Frame representation)")
    return Frame(vecs, key=key)


def parse_parquet(path: str) -> Frame:
    """Parquet ingest via pyarrow — the `h2o-parsers/h2o-parquet-parser`
    extension's role (Parquet is columnar already; no tokenizing phase)."""
    import pyarrow.parquet as pq

    return _arrow_table_to_frame(pq.read_table(path),
                                 key=os.path.basename(path))


def parse_orc(path: str) -> Frame:
    """ORC ingest via pyarrow — the `h2o-parsers/h2o-orc-parser` role."""
    from pyarrow import orc

    return _arrow_table_to_frame(orc.read_table(path),
                                 key=os.path.basename(path))


def import_file(path: str, **kw) -> Frame:
    """`h2o.import_file` — dispatch by extension (`ParseDataset.parse`).
    Non-file URIs (http/s3/gs/hdfs) are fetched through the Persist SPI
    (`runtime/persist.py`, the water.persist backends) into a temp file
    first, then parsed by format as usual."""
    if path.startswith("file://"):
        path = path[len("file://"):]
    if "://" in path:
        import tempfile

        from ..runtime import persist as persist_spi

        import shutil

        backend = persist_spi.for_uri(path)
        suffix = os.path.splitext(path.split("?", 1)[0])[1] or ".csv"
        tmp = tempfile.NamedTemporaryFile(suffix=suffix, delete=False)
        try:
            with backend.open(path) as src:
                shutil.copyfileobj(src, tmp)   # streamed, not buffered
            tmp.close()
            fr = import_file(tmp.name, **kw)
            # key by basename like local parses, but uniquified: two URLs
            # ending in the same filename must not collide in the DKV
            from ..runtime.dkv import DKV

            base = os.path.basename(path.split("?", 1)[0]) or fr.key
            keyname, i = base, 0
            while DKV.get(keyname) is not None:
                i += 1
                keyname = f"{base}_{i}"
            fr.key = keyname
            return fr
        finally:
            os.unlink(tmp.name)
    if os.path.isdir(path):
        # directory import: parse every (non-hidden, optionally
        # pattern-filtered) file and rbind — ParseDataset's multi-file
        # import (`h2o.import_file(path=dir, pattern=...)`)
        import re as _re

        pattern = kw.pop("pattern", None)
        files = sorted(
            os.path.join(path, f) for f in os.listdir(path)
            if not f.startswith(".")
            and os.path.isfile(os.path.join(path, f)))
        if pattern:
            files = [f for f in files
                     if _re.search(pattern, os.path.basename(f))]
        if not files:
            raise ValueError(f"no files to import under {path!r}"
                             + (f" matching {pattern!r}" if pattern else ""))
        out = Frame.rbind_all([import_file(f, **kw) for f in files])
        out.key = os.path.basename(os.path.normpath(path))
        return out
    kw.pop("pattern", None)   # pattern only filters directory imports
    if path.endswith((".svm", ".svmlight")):
        return parse_svmlight(path)
    if path.endswith(".arff"):
        return parse_arff(path)
    if path.endswith((".parquet", ".pq")):
        return parse_parquet(path)
    if path.endswith(".orc"):
        return parse_orc(path)
    import jax

    if jax.process_count() > 1:
        # multi-host cloud: every process parses its own byte range, then
        # the phase-2 collectives agree on types/domains (ParseDataset's
        # MultiFileParseTask + Categorical merge)
        from .distributed_parse import parse_csv_distributed

        return parse_csv_distributed(path, **kw)
    return parse_csv(path, **kw)
