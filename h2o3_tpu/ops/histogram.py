"""tpu_hist — per-(node, feature, bin) gradient histograms.

Reference parity: this op IS the hot loop of the reference's tree engines:
`h2o-algos/src/main/java/hex/tree/DHistogram.java` (`updateHisto`: per-row
per-column accumulate of {count, Σy, Σy²}) driven by
`hex/tree/ScoreBuildHistogram2.java` (the MRTask whose `reduce()` adds
histogram arrays across nodes), and XGBoost's CUDA `gpu_hist` updater
(shipped as `libxgboost4j_gpu.so` in `h2o-ext-xgboost`).

On TPU, scatter-add (the GPU approach: atomics into shared-memory
histograms) is the enemy — the VPU has no atomics and XLA lowers scatter to
serialized updates. On CPU, XLA's scatter emitter is the enemy too: it
loops updates at ~100 ns each. Strategies, selectable and benchmarked:

* ``onehot``: encode (node,bin) as a one-hot matrix and reduce with a
  matmul — rides the MXU. hist[c, l*B+b] = Σ_rows vals[c,row] ·
  onehot[row, l*B+b], scanned over features. O(N·L·B) FLOPs per feature but
  systolic-array FLOPs are nearly free at these sizes.
* ``segment``: `jax.ops.segment_sum` with ids = node·B + bin (XLA sorted
  scatter). The seed CPU default, kept as the ``H2O3_TREE_LEGACY``
  comparator and for very large L·B.
* ``host``: `jax.pure_callback` to a scalar ``np.add.at`` loop — numpy's
  indexed-add fast path runs the SAME sequential in-order f32 fold as the
  XLA scatter at ~10x the speed (measured 16 ms vs 150 ms for 1.4M updates
  on the dev box), so it is bit-exact with ``segment``. The fused-tree CPU
  default for fits >= H2O3_HOST_HIST_MIN_ROWS (32768) padded rows: a
  callback custom-call embeds a process-local pointer, which excludes the
  program from the persistent compile cache — tiny fits keep the cacheable
  ``segment`` program instead of paying a fresh XLA compile per process.
  Consumes 4/5/6-bit packed codes directly, unpacking per row-chunk in
  numpy. Single-shard only (never under a collective), and only when the
  host has a SPARE core (`host_callback_safe`): with one usable CPU the
  XLA CPU runtime deadlocks on any in-graph callback whose operands are
  computed by a large (task-split) op — see `host_callback_safe` — so
  1-core hosts keep the in-graph ``segment`` scatter (bit-identical).
* ``pallas``/``pallas_factored``: the fused VMEM kernels in
  `hist_pallas.py`. With packed input they widen IN-GRAPH once per jitted
  tree program (XLA CSEs the widen across every level's histogram pass of
  the program), so the RESIDENT matrix — what the dataset cache holds
  across fits and what crosses the ~6 MB/s tunnel — stays packed; only a
  program-lifetime transient is full-width. True in-kernel sub-byte decode
  is blocked by Mosaic's (32, 128) int8 tile granularity at the kernel's
  8-feature block shape (see docs/perf.md).

The cross-host combine (ScoreBuildHistogram2.reduce / Rabit allreduce) is a
single `lax.psum` over the ``hosts`` mesh axis, applied by the caller inside
`shard_map` — see `h2o3_tpu/models/tree.py`.

Sharded determinism (ISSUE 12): with ``n_shard_blocks`` > 0 the rows are
accumulated as per-block PARTIAL histograms (each block a contiguous,
equal-sized row range) that are gathered into global block order
(`lax.all_gather`, device-major == row order) and folded LEFT-TO-RIGHT —
a fixed reduction tree independent of how many devices the blocks live
on. An N-device fit and a 1-device fit configured with the same total
block count therefore produce BIT-IDENTICAL histograms: each block
partial is the same sequential in-order f32 fold over the same rows
(`host` np.add.at and the XLA `segment` scatter are pinned bit-exact, so
the mesh lane's in-graph scatter matches the forced-CPU lane's callback),
and the cross-block fold order is pinned by the expression tree. This is
what makes "8-device fit == 1-device fused fit" a bit-stability pin
rather than an allclose hope.

Kernel-selection observability (ISSUE 7): every dispatch records the chosen
method (and the VMEM-pressure pallas→segment fallbacks) into the central
metrics registry, and the tree driver records a per-fit level plan via
``record_fit_plan`` — surfaced at ``GET /3/Profiler`` under ``tree`` so
"which kernel actually ran, at which row_chunk" is never guesswork.
"""

from __future__ import annotations

import functools
import os
import threading
from collections import deque
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import packing

# row-chunk for the host callback's packed unpack (numpy transient bound)
HOST_UNPACK_CHUNK = 1 << 16


def _pallas_available() -> bool:
    from . import hist_pallas

    return hist_pallas._HAVE_PLTPU


def _factored_row_chunk(n_nodes: int, nbins: int) -> int:
    """Largest row chunk whose co-resident VMEM buffers fit: the (3L,R) f32
    scratch and (8B,R) bf16 bin one-hot each ≤8 MB (empirical pass/fail
    boundary on the bench chip) AND scratch + one-hot + the revisited
    (3L,8B) f32 output block ≤16 MB together. Returns <512 when no chunk
    fits (caller falls back to the XLA segment path — recorded, see
    `resolve_method`)."""
    out_bytes = 3 * n_nodes * 8 * nbins * 4
    rc = 8192
    while rc >= 512:
        scratch = 3 * n_nodes * rc * 4
        onehot = 8 * nbins * rc * 2
        if scratch <= (8 << 20) and onehot <= (8 << 20) \
                and scratch + onehot + out_bytes <= (16 << 20):
            break
        rc //= 2
    return rc


# -- kernel-selection observability ----------------------------------------

_SEL_LOCK = threading.Lock()
_SEL_REG: dict = {}
_FIT_PLANS: "deque" = deque(maxlen=16)


def _sel_registry() -> dict:
    """Memoized registry families for kernel-selection counters (same
    memoization stance as runtime/phases._xla_counters)."""
    if not _SEL_REG:
        from ..runtime import metrics_registry as _reg

        _SEL_REG["dispatch"] = _reg.counter(
            "h2o3_tree_hist_dispatch",
            "histogram kernel dispatches by resolved method (trace-time)",
            labelnames=("method",))
        _SEL_REG["vmem_fallbacks"] = _reg.counter(
            "h2o3_tree_hist_vmem_fallbacks",
            "fit-plan levels (per fit, per level) whose pallas_factored "
            "selection fell back to the segment path because no VMEM row "
            "chunk >= 512 fits")
    return _SEL_REG


def resolve_method(n_nodes: int, nbins: int, method: str = "auto",
                   axis_name: Optional[str] = None,
                   platform: Optional[str] = None) -> dict:
    """The ONE auto-dispatch rule, shared by `build_histograms` and the
    driver's per-fit plan recording so the observed plan cannot diverge
    from what actually runs. Returns
    ``{"method", "row_chunk", "fallback"}`` — `row_chunk` is the pallas
    grid chunk (None off the pallas path), `fallback` names why a
    requested kernel was substituted (today: "vmem" for the
    `_factored_row_chunk` < 512 pressure fallback)."""
    if method == "auto":
        method = os.environ.get("H2O3_HIST_METHOD", "auto")
    if platform is None:
        platform = jax.default_backend()
    if method == "auto":
        if platform == "cpu":
            method = "segment"
        elif platform == "tpu":
            # measured on the real chip (1M×28, B=64, BENCH_r02 sweep): the
            # factored pallas kernel is ≥ parity with onehot at L≤16 and
            # 5–14× faster at L≥64 (flat ~10–27 ms vs 130–390 ms)
            method = "pallas_factored" if _pallas_available() else "onehot"
        else:
            method = "onehot"  # non-TPU accelerators: Mosaic won't lower
    row_chunk = None
    fallback = None
    if method == "host" and axis_name is not None:
        # the host callback cannot run under a collective program — the
        # psum'd shard path keeps the in-graph scatter
        method, fallback = "segment", "collective"
    if method == "pallas_factored":
        rc = _factored_row_chunk(n_nodes, nbins)
        if rc < 512:
            # scratch would not fit VMEM at any useful chunk. Deep levels
            # (L·B ≳ 20k) are where XLA's sorted-scatter wins: measured on
            # the real chip (50k×12, B=21) segment is 25–78 ms flat for
            # L=4k..64k vs 64–700 ms for the one-hot matmul paths
            method, fallback = "segment", "vmem"
        else:
            row_chunk = rc
    return {"method": method, "row_chunk": row_chunk, "fallback": fallback}


def _record_selection(sel: dict, vmem: bool = False) -> None:
    """Count a resolution. Each counter has ONE source so the numbers stay
    semantically consistent: `dispatch` counts trace-time kernel dispatches
    (`build_histograms` only — dispatches are rare by design), while
    `vmem_fallbacks` counts per-fit per-level plan entries
    (`record_fit_plan` only, `vmem=True`) — the 'once per fit' satellite
    contract, never double-counted by the trace that follows."""
    try:
        reg = _sel_registry()
        if vmem:
            if sel["fallback"] == "vmem":
                reg["vmem_fallbacks"].inc()
        else:
            reg["dispatch"].inc(1.0, sel["method"])
    except Exception:
        pass


def record_fit_plan(tag: str, levels, nbins: int, hist_method: str,
                    pack_bits: int = 0, axis_name: Optional[str] = None,
                    platform: Optional[str] = None, n_shards: int = 0,
                    n_devices: int = 1) -> dict:
    """Resolve + record the per-level kernel plan of one tree fit.

    `levels` is a sequence of (label, n_nodes) histogram passes the fit
    will run. Logs ONE warning per fit when any level hits the VMEM
    pressure fallback (the previously-silent `_factored_row_chunk` < 512
    path), counts every level's selection in the registry, and keeps the
    plan in a bounded ring surfaced at /3/Profiler."""
    import time as _time

    plan_levels = []
    fellback = []
    for label, n_nodes in levels:
        sel = resolve_method(n_nodes, nbins, hist_method,
                             axis_name=axis_name, platform=platform)
        _record_selection(sel, vmem=True)
        plan_levels.append(dict(level=label, n_nodes=int(n_nodes), **sel))
        if sel["fallback"] == "vmem":
            fellback.append((label, int(n_nodes)))
    plan = dict(tag=tag, ts=_time.time(), nbins=int(nbins),
                hist_method=hist_method, pack_bits=int(pack_bits),
                n_shards=int(n_shards), n_devices=int(n_devices),
                levels=plan_levels)
    if fellback:
        from ..runtime.log import Log

        Log.warn(
            f"tree fit {tag}: histogram levels {fellback} exceed the VMEM "
            f"row-chunk floor — falling back to the segment kernel "
            "(counted in h2o3_tree_hist_vmem_fallbacks)")
    with _SEL_LOCK:
        _FIT_PLANS.append(plan)
    return plan


def attach_fit_stream(tag: str, stream: dict) -> None:
    """Attach a finished fit's out-of-core stream summary (blocks
    uploaded/evicted/reused, bytes streamed, bytes per tree, resident
    peak) to its recorded plan — the ISSUE 14 observability contract:
    the tree fold at /3/Profiler carries the streaming trajectory next
    to the kernel plan, so 'how many bytes did this fit move per tree'
    is a read, not a rerun."""
    with _SEL_LOCK:
        for plan in reversed(_FIT_PLANS):
            if plan["tag"] == tag:
                plan["stream"] = dict(stream)
                return


def attach_fit_skew(tag: str, skew: dict) -> None:
    """Attach a finished fit's collective-skew summary (mesh.lane_summary)
    to its recorded plan — the plan rings at /3/Profiler `tree` then carry
    per-fit {fences, skew_p50_ms, skew_max_ms, worst_lane} next to the
    kernel plan (ISSUE 13: per-fit skew summaries in the tree fold)."""
    with _SEL_LOCK:
        for plan in reversed(_FIT_PLANS):
            if plan["tag"] == tag:
                plan["collective_skew"] = dict(skew)
                return


def kernel_stats() -> dict:
    """Per-fit kernel plans + cumulative dispatch counters (the /3/Profiler
    `tree` fold). Pure counter read."""
    with _SEL_LOCK:
        plans = list(_FIT_PLANS)
    out = dict(plans=plans, dispatch={}, vmem_fallbacks=0)
    try:
        reg = _sel_registry()
        out["dispatch"] = {lv[0]: c.value()
                           for lv, c in reg["dispatch"].children().items()}
        out["vmem_fallbacks"] = reg["vmem_fallbacks"].value()
    except Exception:
        pass
    return out


# -- kernels ----------------------------------------------------------------


def _hist_onehot(codes, node_id, vals, n_nodes: int, nbins: int):
    """MXU path. codes (N,F) int, node_id (N,) int, vals (3,N) f32.
    Returns (n_nodes, F, nbins, 3).

    Factored one-hot: the (node × channel)-weighted matrix (3L, N) is built
    ONCE per level and shared by every feature; each scan step only builds
    the (N, B) bin one-hot and runs one (3L,N)@(N,B) MXU matmul. This does
    N·B comparisons per feature instead of N·L·B — the VPU (comparison) work
    no longer scales with the node count."""
    N, F = codes.shape
    if 3 * n_nodes * N * 2 > (256 << 20):
        # deep levels: the shared (3L, N) weighted matrix would not fit —
        # fall back to the fused (node,bin) one-hot inside the scan
        LB = n_nodes * nbins
        base = node_id.astype(jnp.int32) * nbins
        iota = jnp.arange(LB, dtype=jnp.int32)

        def one_feature_fused(carry, code_f):
            cid = base + code_f.astype(jnp.int32)
            onehot = (cid[:, None] == iota[None, :]).astype(jnp.bfloat16)
            hist_f = jnp.dot(vals.astype(jnp.bfloat16), onehot,
                             preferred_element_type=jnp.float32)  # (3, LB)
            return carry, hist_f

        _, hists = jax.lax.scan(one_feature_fused, None, codes.T)
        return hists.reshape(F, 3, n_nodes, nbins).transpose(2, 0, 3, 1)

    node_oh = (node_id[:, None].astype(jnp.int32)
               == jnp.arange(n_nodes, dtype=jnp.int32)[None, :]).astype(jnp.bfloat16)
    weighted = vals.astype(jnp.bfloat16)[:, :, None] * node_oh[None, :, :]  # (3,N,L)
    weighted = weighted.transpose(0, 2, 1).reshape(3 * n_nodes, N)          # (3L,N)
    iota_b = jnp.arange(nbins, dtype=jnp.int32)

    def one_feature(carry, code_f):
        bin_oh = (code_f[:, None].astype(jnp.int32) == iota_b[None, :]).astype(jnp.bfloat16)
        hist_f = jnp.dot(weighted, bin_oh, preferred_element_type=jnp.float32)  # (3L,B)
        return carry, hist_f

    _, hists = jax.lax.scan(one_feature, None, codes.T)   # (F, 3L, B)
    return hists.reshape(F, 3, n_nodes, nbins).transpose(2, 0, 3, 1)


def _hist_segment(codes, node_id, vals, n_nodes: int, nbins: int):
    """Sorted-scatter path. Returns (n_nodes, F, nbins, 3)."""
    N, F = codes.shape
    base = node_id.astype(jnp.int32) * nbins

    def one_feature(carry, code_f):
        ids = base + code_f.astype(jnp.int32)
        hist_f = jax.ops.segment_sum(vals.T, ids, num_segments=n_nodes * nbins)  # (LB,3)
        return carry, hist_f

    _, hists = jax.lax.scan(one_feature, None, codes.T)   # (F, LB, 3)
    return hists.reshape(F, n_nodes, nbins, 3).transpose(1, 0, 2, 3)


def _host_hist_cb(codes, node_id, vals, n_nodes: int, nbins: int,
                  pack_bits: int) -> np.ndarray:
    """The host accumulate loop: scalar ``np.add.at`` per (feature,
    channel) — numpy's indexed-add fast path, a sequential in-order f32
    fold bit-identical to the XLA scatter the `segment` path runs.
    Packed codes are widened per `HOST_UNPACK_CHUNK` rows, so the
    full-width matrix never materializes."""
    codes = np.asarray(codes)
    node_id = np.asarray(node_id, dtype=np.int32)
    vals = np.asarray(vals)
    F = codes.shape[1]
    LB = n_nodes * nbins
    out = np.zeros((F, LB, 3), np.float32)
    base_all = node_id * np.int32(nbins)
    n = (packing.packed_nrows(codes.shape[0], pack_bits) if pack_bits
         else codes.shape[0])
    group = packing.GROUP_ROWS.get(pack_bits, 1)
    gbytes = packing.GROUP_BYTES.get(pack_bits, 1)
    step = HOST_UNPACK_CHUNK - (HOST_UNPACK_CHUNK % group or 0)
    for r0 in range(0, n, step):
        r1 = min(r0 + step, n)
        if pack_bits:
            chunk = packing.unpack_host(
                codes[r0 // group * gbytes: r1 // group * gbytes], pack_bits)
        else:
            chunk = codes[r0:r1]
        base = base_all[r0:r1]
        for f in range(F):
            ids = base + chunk[:, f].astype(np.int32)
            for k in range(3):
                np.add.at(out[f, :, k], ids, vals[k, r0:r1])
    return out.reshape(F, n_nodes, nbins, 3).transpose(1, 0, 2, 3)


def _hist_host(codes, node_id, vals, n_nodes: int, nbins: int,
               pack_bits: int):
    """`pure_callback` wrapper around `_host_hist_cb` (CPU fast path).

    The callback BODY runs on the ONE dedicated host-hist worker thread
    (round 19): hopping to the worker serializes every host accumulate —
    warm thread and fit included — so concurrent dispatches can't thrash
    numpy's indexed-add fast path, and XLA's callback thread just waits
    on the future. Operands are materialized to numpy BEFORE the hop, on
    the thread XLA handed us: a device->host conversion from the worker
    thread would wait on the runtime while the runtime waits on our
    future. Requires a spare core — `host_callback_safe` gates selection
    (see the comment block below)."""
    F = codes.shape[1]

    def cb(codes_, node_id_, vals_):
        # materialize to numpy HERE, on the thread XLA handed us: a
        # device->host conversion from the worker thread would wait on
        # the runtime while the runtime waits on our future
        codes_ = np.asarray(codes_)
        node_id_ = np.asarray(node_id_)
        vals_ = np.asarray(vals_)
        return _host_worker().submit(
            _host_hist_cb, codes_, node_id_, vals_,
            n_nodes=n_nodes, nbins=nbins, pack_bits=pack_bits).result()

    return jax.pure_callback(
        cb, jax.ShapeDtypeStruct((n_nodes, F, nbins, 3), jnp.float32),
        codes, node_id, vals)


# -- dedicated host-histogram worker (ISSUE 14 satellite) -------------------
#
# The in-graph `pure_callback` route has a known failure mode on 1-core
# hosts, root-caused in round 19 (it was previously blamed on the warm-up
# thread; a pristine fit with H2O3_WARM_THREAD=0 hangs identically): the
# XLA CPU runtime splits large ops into parallel tasks on its intra-op
# pool, and with ONE usable core the pool's only thread is the very thread
# that ends up blocked inside the callback custom-call — the producer
# tasks behind it never drain, so `np.asarray` on any computed operand
# over the task-split threshold (~256 KB) waits forever. Reproduced with a
# 12-line minimal jit(pure_callback) at 32768x8 f32; operands that are
# program INPUTS or small reductions are unaffected. `host_callback_safe`
# below gates the auto host-method selection on a spare core; 1-core
# hosts keep the in-graph `segment` scatter, which is pinned bit-exact.
# The STREAMED tree path never goes through pure_callback at all: its
# per-block host histograms run `_host_hist_cb` directly on ONE dedicated
# worker thread — same math, no XLA callback machinery to hang, and
# serialization keeps numpy's indexed-add fast path from thrashing the
# host — so big CPU fits on 1-core hosts still get the np.add.at win via
# the out-of-core streaming lane (auto at >= the stream budget).

_HOST_WORKER_LOCK = threading.Lock()
_HOST_WORKER = [None]


def host_callback_safe() -> bool:
    """True when the CPU runtime has a spare thread to service an
    in-graph host callback. With one usable core, XLA's intra-op pool
    cannot make progress on the callback's producer ops while the
    callback blocks (deadlock — see the comment block above), so the
    fused path must keep the in-graph `segment` kernel there."""
    try:
        n = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        n = os.cpu_count() or 1
    return n > 1


def _host_worker():
    if _HOST_WORKER[0] is None:
        with _HOST_WORKER_LOCK:
            if _HOST_WORKER[0] is None:
                from concurrent.futures import ThreadPoolExecutor

                _HOST_WORKER[0] = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="h2o3-host-hist")
    return _HOST_WORKER[0]


def host_hist_direct(codes: np.ndarray, node_id: np.ndarray,
                     vals: np.ndarray, n_nodes: int, nbins: int,
                     pack_bits: int) -> np.ndarray:
    """One host-histogram accumulate, routed through the single dedicated
    callback worker (never `pure_callback`). Bit-exact with `_hist_host`
    / the `segment` scatter — the streamed-block host path."""
    return _host_worker().submit(
        _host_hist_cb, codes, node_id, vals,
        n_nodes=n_nodes, nbins=nbins, pack_bits=pack_bits).result()


def run_block_kernel(method: str, codes, node_id, vals, n_nodes: int,
                     nbins: int, pack_bits: int = 0,
                     row_chunk: "Optional[int]" = None):
    """One resolved kernel over one contiguous row block — the public
    entry the streamed out-of-core driver jits per block. Identical to
    each per-block partial of the blocked in-core reduction
    (`build_histograms` with ``n_shard_blocks``), which is what makes a
    streamed fit bit-identical to the in-core blocks fit."""
    return _run_kernel({"method": method, "row_chunk": row_chunk,
                        "fallback": None},
                       codes, node_id, vals, n_nodes, nbins, pack_bits)


def ordered_axis_fold(parts: jax.Array, axis_name: Optional[str],
                      timing_tag: Optional[str] = None) -> jax.Array:
    """Deterministic sum of per-block partials: gather the (local_blocks,
    ...) stack into GLOBAL block order (`all_gather` is device-major, which
    matches row order for contiguous row sharding) and fold left-to-right —
    the association is pinned by the expression tree, so the result is
    independent of how the blocks are distributed over devices. The
    shard-invariant replacement for `lax.psum` on the deterministic tree
    path (psum's reduction order is implementation-defined).

    ``timing_tag`` attaches the per-lane collective skew instrument
    (`mesh.lane_mark`, ISSUE 13): each lane stamps a host timestamp the
    moment its partial is ready, barrier-ordered before the all_gather, so
    the fence's per-lane waits are observable. Values are untouched (the
    mark is an identity + io_callback), preserving the bit-stability
    contract above. Only the per-scoring-interval callers pass a tag —
    the per-level histogram passes stay uninstrumented."""
    if axis_name is not None:
        if timing_tag is not None:
            from ..parallel import mesh as _mesh

            if _mesh.lane_timing_enabled():
                parts = _mesh.lane_mark(parts, axis_name, timing_tag)
        parts = jax.lax.all_gather(parts, axis_name, axis=0, tiled=False)
        parts = parts.reshape((-1,) + parts.shape[2:])
    acc = parts[0]
    for i in range(1, parts.shape[0]):
        acc = acc + parts[i]
    return acc


def _run_kernel(sel: dict, codes, node_id, vals, n_nodes: int, nbins: int,
                pack_bits: int):
    """One resolved kernel invocation over one contiguous row range."""
    method = sel["method"]
    if method == "host":
        return _hist_host(codes, node_id, vals, n_nodes, nbins, pack_bits)
    if pack_bits:
        # in-graph consumers take dense codes: widen in-graph. The widen is
        # a pure function of the loop-invariant packed input, so XLA
        # computes it once per program execution and shares the buffer
        # across every level's histogram pass; the RESIDENT matrix stays
        # packed
        codes = packing.unpack_device(codes, pack_bits)
    if method == "onehot":
        return _hist_onehot(codes, node_id, vals, n_nodes, nbins)
    if method == "segment":
        return _hist_segment(codes, node_id, vals, n_nodes, nbins)
    if method == "pallas":
        from . import hist_pallas

        return hist_pallas.build_histograms_pallas(
            codes, node_id, vals, n_nodes, nbins)
    if method == "pallas_factored":
        from . import hist_pallas

        return hist_pallas.build_histograms_pallas_factored(
            codes.T.astype(jnp.float32), node_id, vals, n_nodes, nbins,
            row_chunk=sel["row_chunk"],
        )
    raise ValueError(f"unknown histogram method {method!r}")


def _packed_row_slice(codes, r0: int, r1: int, pack_bits: int):
    """Rows [r0, r1) of a (possibly packed) code matrix. Block boundaries
    are multiples of 8 rows, so they always align with pack groups."""
    if not pack_bits:
        return codes[r0:r1]
    group = packing.GROUP_ROWS[pack_bits]
    gbytes = packing.GROUP_BYTES[pack_bits]
    return codes[r0 // group * gbytes: r1 // group * gbytes]


def build_histograms(
    codes: jax.Array,
    node_id: jax.Array,
    g: jax.Array,
    h: jax.Array,
    w: jax.Array,
    n_nodes: int,
    nbins: int,
    method: str = "auto",
    axis_name: Optional[str] = None,
    pack_bits: int = 0,
    n_shard_blocks: int = 0,
) -> jax.Array:
    """Histogram of {Σw, Σg, Σh} per (tree-node, feature, bin).

    Rows with w==0 (padding, row-sampling dropouts, OOB) contribute nothing —
    g/h/w must already be masked by the caller. `axis_name` triggers the
    cross-host merge (the MRTask.reduce step) when called under shard_map.

    With ``pack_bits`` in {4, 5, 6}, `codes` is the `ops.packing` packed
    matrix; the host and pallas paths consume it directly (per-row-chunk
    unpack), other paths widen in-graph before accumulating.

    ``n_shard_blocks`` > 0 switches to the shard-invariant blocked
    reduction (see module docstring): this call's rows are split into that
    many equal contiguous blocks, each accumulated independently by the
    SAME kernel, and the partials fold deterministically across blocks and
    (under `axis_name`) across devices. The caller guarantees rows divide
    evenly (padded row counts are multiples of blocks·8).
    """
    vals = jnp.stack([w, g * w, h * w]).astype(jnp.float32)  # (3, N)
    sel = resolve_method(n_nodes, nbins, method, axis_name=axis_name)
    _record_selection(sel)
    if n_shard_blocks > 0:
        n = node_id.shape[0]
        if n % n_shard_blocks:
            raise ValueError(
                f"{n} rows do not divide into {n_shard_blocks} shard blocks")
        rows = n // n_shard_blocks
        parts = []
        for b in range(n_shard_blocks):
            parts.append(_run_kernel(
                sel, _packed_row_slice(codes, b * rows, (b + 1) * rows,
                                       pack_bits),
                node_id[b * rows:(b + 1) * rows],
                vals[:, b * rows:(b + 1) * rows],
                n_nodes, nbins, pack_bits))
        return ordered_axis_fold(jnp.stack(parts), axis_name)
    hist = _run_kernel(sel, codes, node_id, vals, n_nodes, nbins, pack_bits)
    if axis_name is not None:
        hist = jax.lax.psum(hist, axis_name)
    return hist  # (n_nodes, F, nbins, 3) — [..., 0]=Σw [..., 1]=Σg [..., 2]=Σh
