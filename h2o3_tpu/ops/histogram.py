"""tpu_hist — per-(node, feature, bin) gradient histograms.

Reference parity: this op IS the hot loop of the reference's tree engines:
`h2o-algos/src/main/java/hex/tree/DHistogram.java` (`updateHisto`: per-row
per-column accumulate of {count, Σy, Σy²}) driven by
`hex/tree/ScoreBuildHistogram2.java` (the MRTask whose `reduce()` adds
histogram arrays across nodes), and XGBoost's CUDA `gpu_hist` updater
(shipped as `libxgboost4j_gpu.so` in `h2o-ext-xgboost`).

On TPU, scatter-add (the GPU approach: atomics into shared-memory
histograms) is the enemy — the VPU has no atomics and XLA lowers scatter to
serialized updates. Two TPU-shaped strategies, selectable and benchmarked:

* ``onehot``: encode (node,bin) as a one-hot matrix and reduce with a
  matmul — rides the MXU. hist[c, l*B+b] = Σ_rows vals[c,row] ·
  onehot[row, l*B+b], scanned over features. O(N·L·B) FLOPs per feature but
  systolic-array FLOPs are nearly free at these sizes.
* ``segment``: `jax.ops.segment_sum` with ids = node·B + bin (XLA sorted
  scatter). Wins on CPU and for very large L·B.

The cross-host combine (ScoreBuildHistogram2.reduce / Rabit allreduce) is a
single `lax.psum` over the ``hosts`` mesh axis, applied by the caller inside
`shard_map` — see `h2o3_tpu/models/tree.py`.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _pallas_available() -> bool:
    from . import hist_pallas

    return hist_pallas._HAVE_PLTPU


def _factored_row_chunk(n_nodes: int, nbins: int) -> int:
    """Largest row chunk whose co-resident VMEM buffers fit: the (3L,R) f32
    scratch and (8B,R) bf16 bin one-hot each ≤8 MB (empirical pass/fail
    boundary on the bench chip) AND scratch + one-hot + the revisited
    (3L,8B) f32 output block ≤16 MB together. Returns <512 when no chunk
    fits (caller falls back to the XLA onehot path)."""
    out_bytes = 3 * n_nodes * 8 * nbins * 4
    rc = 8192
    while rc >= 512:
        scratch = 3 * n_nodes * rc * 4
        onehot = 8 * nbins * rc * 2
        if scratch <= (8 << 20) and onehot <= (8 << 20) \
                and scratch + onehot + out_bytes <= (16 << 20):
            break
        rc //= 2
    return rc


def _hist_onehot(codes, node_id, vals, n_nodes: int, nbins: int):
    """MXU path. codes (N,F) int, node_id (N,) int, vals (3,N) f32.
    Returns (n_nodes, F, nbins, 3).

    Factored one-hot: the (node × channel)-weighted matrix (3L, N) is built
    ONCE per level and shared by every feature; each scan step only builds
    the (N, B) bin one-hot and runs one (3L,N)@(N,B) MXU matmul. This does
    N·B comparisons per feature instead of N·L·B — the VPU (comparison) work
    no longer scales with the node count."""
    N, F = codes.shape
    if 3 * n_nodes * N * 2 > (256 << 20):
        # deep levels: the shared (3L, N) weighted matrix would not fit —
        # fall back to the fused (node,bin) one-hot inside the scan
        LB = n_nodes * nbins
        base = node_id.astype(jnp.int32) * nbins
        iota = jnp.arange(LB, dtype=jnp.int32)

        def one_feature_fused(carry, code_f):
            cid = base + code_f.astype(jnp.int32)
            onehot = (cid[:, None] == iota[None, :]).astype(jnp.bfloat16)
            hist_f = jnp.dot(vals.astype(jnp.bfloat16), onehot,
                             preferred_element_type=jnp.float32)  # (3, LB)
            return carry, hist_f

        _, hists = jax.lax.scan(one_feature_fused, None, codes.T)
        return hists.reshape(F, 3, n_nodes, nbins).transpose(2, 0, 3, 1)

    node_oh = (node_id[:, None].astype(jnp.int32)
               == jnp.arange(n_nodes, dtype=jnp.int32)[None, :]).astype(jnp.bfloat16)
    weighted = vals.astype(jnp.bfloat16)[:, :, None] * node_oh[None, :, :]  # (3,N,L)
    weighted = weighted.transpose(0, 2, 1).reshape(3 * n_nodes, N)          # (3L,N)
    iota_b = jnp.arange(nbins, dtype=jnp.int32)

    def one_feature(carry, code_f):
        bin_oh = (code_f[:, None].astype(jnp.int32) == iota_b[None, :]).astype(jnp.bfloat16)
        hist_f = jnp.dot(weighted, bin_oh, preferred_element_type=jnp.float32)  # (3L,B)
        return carry, hist_f

    _, hists = jax.lax.scan(one_feature, None, codes.T)   # (F, 3L, B)
    return hists.reshape(F, 3, n_nodes, nbins).transpose(2, 0, 3, 1)


def _hist_segment(codes, node_id, vals, n_nodes: int, nbins: int):
    """Sorted-scatter path. Returns (n_nodes, F, nbins, 3)."""
    N, F = codes.shape
    base = node_id.astype(jnp.int32) * nbins

    def one_feature(carry, code_f):
        ids = base + code_f.astype(jnp.int32)
        hist_f = jax.ops.segment_sum(vals.T, ids, num_segments=n_nodes * nbins)  # (LB,3)
        return carry, hist_f

    _, hists = jax.lax.scan(one_feature, None, codes.T)   # (F, LB, 3)
    return hists.reshape(F, n_nodes, nbins, 3).transpose(1, 0, 2, 3)


def build_histograms(
    codes: jax.Array,
    node_id: jax.Array,
    g: jax.Array,
    h: jax.Array,
    w: jax.Array,
    n_nodes: int,
    nbins: int,
    method: str = "auto",
    axis_name: Optional[str] = None,
) -> jax.Array:
    """Histogram of {Σw, Σg, Σh} per (tree-node, feature, bin).

    Rows with w==0 (padding, row-sampling dropouts, OOB) contribute nothing —
    g/h/w must already be masked by the caller. `axis_name` triggers the
    cross-host psum (the MRTask.reduce step) when called under shard_map.
    """
    vals = jnp.stack([w, g * w, h * w]).astype(jnp.float32)  # (3, N)
    if method == "auto":
        method = os.environ.get("H2O3_HIST_METHOD", "auto")
    if method == "auto":
        platform = jax.default_backend()
        if platform == "cpu":
            method = "segment"
        elif platform == "tpu":
            # measured on the real chip (1M×28, B=64, BENCH_r02 sweep): the
            # factored pallas kernel is ≥ parity with onehot at L≤16 and
            # 5–14× faster at L≥64 (flat ~10–27 ms vs 130–390 ms)
            method = "pallas_factored" if _pallas_available() else "onehot"
        else:
            method = "onehot"  # non-TPU accelerators: Mosaic won't lower
    if method == "onehot":
        hist = _hist_onehot(codes, node_id, vals, n_nodes, nbins)
    elif method == "segment":
        hist = _hist_segment(codes, node_id, vals, n_nodes, nbins)
    elif method == "pallas":
        from . import hist_pallas

        hist = hist_pallas.build_histograms_pallas(codes, node_id, vals, n_nodes, nbins)
    elif method == "pallas_factored":
        from . import hist_pallas

        rc = _factored_row_chunk(n_nodes, nbins)
        if rc < 512:
            # scratch would not fit VMEM at any useful chunk. Deep levels
            # (L·B ≳ 20k) are where XLA's sorted-scatter wins: measured on
            # the real chip (50k×12, B=21) segment is 25–78 ms flat for
            # L=4k..64k vs 64–700 ms for the one-hot matmul paths
            hist = _hist_segment(codes, node_id, vals, n_nodes, nbins)
        else:
            hist = hist_pallas.build_histograms_pallas_factored(
                codes.T.astype(jnp.float32), node_id, vals, n_nodes, nbins,
                row_chunk=rc,
            )
    else:
        raise ValueError(f"unknown histogram method {method!r}")
    if axis_name is not None:
        hist = jax.lax.psum(hist, axis_name)
    return hist  # (n_nodes, F, nbins, 3) — [..., 0]=Σw [..., 1]=Σg [..., 2]=Σh
