"""Cubic regression-spline basis — numpy only.

Shared by GAM training (`models/gam.py`) and the offline MOJO scorer
(`mojo.py`), which must stay importable without JAX at serve time.
Reference: `hex/gam/MatrixFrameUtils/GamUtils.java` basis generation
(`bs=0` cr-splines).
"""

from __future__ import annotations

import numpy as np


def spline_basis(col: np.ndarray, knots: np.ndarray) -> np.ndarray:
    """Natural cubic regression spline basis on the given interior knots
    (the reference's `bs=0` cr-spline), K knots → K−1 basis columns (the
    constant column is dropped — absorbed by the model intercept)."""
    K = len(knots)
    kmin, kmax = knots[0], knots[-1]
    rng = max(kmax - kmin, 1e-12)

    def d(z, kj):  # truncated cubic, scaled for conditioning
        t = np.maximum(z - kj, 0.0) / rng
        return t**3

    # natural spline: linear beyond boundary knots (Royston/Parmar form)
    cols = [np.ones_like(col), (col - kmin) / rng]
    for j in range(1, K - 1):
        lam = (kmax - knots[j]) / rng
        cols.append(d(col, knots[j]) - lam * d(col, kmin) - (1 - lam) * d(col, kmax))
    return np.column_stack(cols[1:])  # drop the constant (absorbed by intercept)


def second_diff_penalty(m: int) -> np.ndarray:
    """S = D'D with D the second-difference operator — the standard P-spline
    roughness penalty standing in for the cr-spline integral penalty."""
    if m < 3:
        return np.eye(m) * 1e-3
    D = np.zeros((m - 2, m))
    for i in range(m - 2):
        D[i, i : i + 3] = (1.0, -2.0, 1.0)
    return D.T @ D
