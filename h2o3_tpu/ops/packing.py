"""Sub-byte bin-code packing — the device-resident compressed code matrix.

The quantized (N, F) bin-code matrix is both the dominant fixed H2D cost
(a remote-chip tunnel moves ~6 MB/s) and, once resident, the dominant
per-level HBM read of the tree hot loop (every histogram pass streams it).
4/5/6-bit packing cuts both 2-4x — the ELLPACK-style compressed storage of
"XGBoost: Scalable GPU Accelerated Learning" (arXiv 1806.11248), which
keeps bit-packed feature codes resident and decodes in-kernel.

Layout: codes are packed ALONG ROWS in fixed groups so any row-slice at a
group boundary unpacks standalone (row-chunked consumers never touch
neighbouring groups):

=====  ==========  ===========  =========================================
bits   rows/group  bytes/group  bitstream
=====  ==========  ===========  =========================================
4      2           1            row codes MSB-first, 4 bits each
5      8           5            row codes MSB-first, 5 bits each
6      4           3            row codes MSB-first, 6 bits each
=====  ==========  ===========  =========================================

Consumers:

* ``unpack_device`` — whole-matrix widening on device (the legacy
  ``H2O3_TREE_LEGACY=1`` path: ship packed, materialize full width once).
* ``ops/histogram.py`` — the host callback path unpacks in numpy per
  64k-row chunk (the full-width matrix never exists); in-graph kernels
  widen once per jitted tree program (a program-lifetime transient — the
  resident matrix stays packed).
* ``packed_row_values`` — the partition step's per-row selected-feature
  code, extracted straight from the packed words (two byte gathers + a
  shift per row).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# rows per pack group / packed bytes per group, by bit width
GROUP_ROWS = {4: 2, 5: 8, 6: 4}
GROUP_BYTES = {4: 1, 5: 5, 6: 3}


def pack_bits_for(nbins: int, nrows: int) -> int:
    """Narrowest usable packing for codes < nbins (0 = ship unpacked).
    Rows must be a multiple of the group size (padded row counts are
    multiples of 8)."""
    for bits, group in ((4, 2), (5, 8), (6, 4)):
        if nbins <= (1 << bits) and nrows % group == 0:
            return bits
    return 0


def packed_nrows(packed_rows: int, bits: int) -> int:
    """Unpacked row count of a packed array with `packed_rows` rows."""
    return packed_rows * 8 // bits


def pack_host(codes: np.ndarray, bits: int) -> np.ndarray:
    """Pack uint8 bin codes < 2^bits into `bits` bits per value along rows.
    bits ∈ {4, 5, 6}: {2, 8, 4} row-groups → {1, 5, 3} bytes."""
    if bits == 4:
        return (codes[0::2] << 4) | codes[1::2]
    if bits == 5:
        a, b, c, d, e, f, g, hh = (codes[i::8] for i in range(8))
        out = np.empty((5 * a.shape[0],) + codes.shape[1:], np.uint8)
        out[0::5] = (a << 3) | (b >> 2)
        out[1::5] = ((b & 0x3) << 6) | (c << 1) | (d >> 4)
        out[2::5] = ((d & 0xF) << 4) | (e >> 1)
        out[3::5] = ((e & 0x1) << 7) | (f << 2) | (g >> 3)
        out[4::5] = ((g & 0x7) << 5) | hh
        return out
    # 6-bit: stays uint8 end to end (max 63<<2 = 252)
    a, b, c, d = codes[0::4], codes[1::4], codes[2::4], codes[3::4]
    out = np.empty((3 * a.shape[0],) + codes.shape[1:], np.uint8)
    out[0::3] = (a << 2) | (b >> 4)
    out[1::3] = ((b & 0xF) << 4) | (c >> 2)
    out[2::3] = ((c & 0x3) << 6) | d
    return out


def pack_host_range(codes: np.ndarray, bits: int, r0: int, r1: int) -> np.ndarray:
    """Pack rows ``[r0, r1)`` of a full-width code matrix — the block-wise
    ingest half of the out-of-core path (ISSUE 14): building one streamed
    block touches O(block) host memory (a view slice plus the packed block
    output), never a whole-matrix packed transient. `r0`/`r1` must sit on
    pack-group boundaries (block grids are multiples of 8 rows, and every
    group size divides 8), so the block's bitstream is byte-identical to
    the corresponding slice of a whole-matrix `pack_host`."""
    group = GROUP_ROWS[bits]
    if r0 % group or r1 % group:
        raise ValueError(
            f"block [{r0}, {r1}) is not aligned to the {group}-row pack "
            f"group of {bits}-bit codes")
    return pack_host(codes[r0:r1], bits)


def unpack_host(packed: np.ndarray, bits: int) -> np.ndarray:
    """Inverse of `pack_host` on host numpy (the histogram callback's
    per-chunk widening) — bit-exact with `unpack_device`."""
    if bits == 4:
        k = packed.shape[0]
        out = np.empty((2 * k,) + packed.shape[1:], np.uint8)
        out[0::2] = packed >> 4
        out[1::2] = packed & 0xF
        return out
    if bits == 5:
        b = [packed[i::5].astype(np.uint16) for i in range(5)]
        k = packed.shape[0] // 5
        out = np.empty((8 * k,) + packed.shape[1:], np.uint8)
        out[0::8] = b[0] >> 3
        out[1::8] = ((b[0] & 0x7) << 2) | (b[1] >> 6)
        out[2::8] = (b[1] >> 1) & 0x1F
        out[3::8] = ((b[1] & 0x1) << 4) | (b[2] >> 4)
        out[4::8] = ((b[2] & 0xF) << 1) | (b[3] >> 7)
        out[5::8] = (b[3] >> 2) & 0x1F
        out[6::8] = ((b[3] & 0x3) << 3) | (b[4] >> 5)
        out[7::8] = b[4] & 0x1F
        return out
    b0 = packed[0::3].astype(np.uint16)
    b1 = packed[1::3].astype(np.uint16)
    b2 = packed[2::3].astype(np.uint16)
    k = packed.shape[0] // 3
    out = np.empty((4 * k,) + packed.shape[1:], np.uint8)
    out[0::4] = b0 >> 2
    out[1::4] = ((b0 & 0x3) << 4) | (b1 >> 4)
    out[2::4] = ((b1 & 0xF) << 2) | (b2 >> 6)
    out[3::4] = b2 & 0x3F
    return out


@functools.partial(jax.jit, static_argnames=("bits",))
def unpack_device(packed, bits: int):
    """Inverse of pack_host, on device: one widening program."""
    if bits == 4:
        k = packed.shape[0]
        out = jnp.stack([packed >> 4, packed & 0xF], axis=1)
        return out.reshape((2 * k,) + packed.shape[1:]).astype(jnp.uint8)
    if bits == 5:
        b = [packed[i::5].astype(jnp.uint16) for i in range(5)]
        k = packed.shape[0] // 5
        vals = [
            b[0] >> 3,
            ((b[0] & 0x7) << 2) | (b[1] >> 6),
            (b[1] >> 1) & 0x1F,
            ((b[1] & 0x1) << 4) | (b[2] >> 4),
            ((b[2] & 0xF) << 1) | (b[3] >> 7),
            (b[3] >> 2) & 0x1F,
            ((b[3] & 0x3) << 3) | (b[4] >> 5),
            b[4] & 0x1F,
        ]
        out = jnp.stack(vals, axis=1).reshape((8 * k,) + packed.shape[1:])
        return out.astype(jnp.uint8)
    b0 = packed[0::3].astype(jnp.uint16)
    b1 = packed[1::3].astype(jnp.uint16)
    b2 = packed[2::3].astype(jnp.uint16)
    a = b0 >> 2
    b = ((b0 & 0x3) << 4) | (b1 >> 4)
    c = ((b1 & 0xF) << 2) | (b2 >> 6)
    d = b2 & 0x3F
    k = packed.shape[0] // 3
    out = jnp.stack([a, b, c, d], axis=1).reshape((4 * k,) + packed.shape[1:])
    return out.astype(jnp.uint8)


def packed_row_values(packed: jax.Array, rf: jax.Array, bits: int) -> jax.Array:
    """codes[i, rf[i]] as int32, read straight from the packed words —
    the per-row selected-feature code of the partition step.

    A row's code spans at most two adjacent bytes of its group's
    bitstream; two flat gathers + one shift recover it exactly. When the
    code sits entirely in byte0 the second gather (clamped in-bounds) is
    shifted out, so no group ever reads past its own bytes."""
    P, F = packed.shape
    rows_per = GROUP_ROWS[bits]
    bytes_per = GROUP_BYTES[bits]
    n = P * 8 // bits
    i = jnp.arange(n, dtype=jnp.int32)
    grp = i // rows_per
    bit0 = (i % rows_per) * bits
    b0 = grp * bytes_per + bit0 // 8
    off = bit0 % 8
    b1 = jnp.minimum(b0 + 1, P - 1)
    flat = packed.reshape(-1).astype(jnp.int32)
    rfi = rf.astype(jnp.int32)
    v0 = flat[b0 * F + rfi]
    v1 = flat[b1 * F + rfi]
    return (((v0 << 8) | v1) >> (16 - bits - off)) & ((1 << bits) - 1)
