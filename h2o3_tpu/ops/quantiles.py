"""Distributed quantiles — the multi-host edge-finding primitive.

Reference parity: `h2o-algos/src/main/java/hex/quantile/Quantile.java` —
the exact distributed quantile MRTask that feeds `QuantilesGlobal`
histograms and quantile loss: per-node value histograms are tree-reduced,
the target bin located from merged counts, then refined by re-histogramming
inside that bin. On TPU the same two ideas become one compiled program:

* per-shard fixed-width histogram over the global [min, max] range —
  `lax.psum` merges shards (the MRTask.reduce step);
* iterative refinement re-histograms inside the bracketing bin, so k
  rounds give (nbins)^k effective resolution without sorting or gathering
  row data across hosts.

Runs under `shard_map` with rows sharded over the ``hosts`` axis; on one
device it degenerates to plain histogramming (axis_name=None).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


@functools.partial(
    jax.jit, static_argnames=("probs", "nbins", "iters", "axis_name")
)
def distributed_quantiles(
    x: jax.Array,            # (N,) shard-local values (NaN = NA, ignored)
    w: jax.Array,            # (N,) shard-local weights (0 masks rows/padding)
    probs: tuple,            # quantile probabilities, static
    nbins: int = 1024,
    iters: int = 3,
    axis_name: Optional[str] = None,
):
    """Weighted quantiles of the global (cross-shard) distribution.

    Returns (len(probs),) values. Accuracy: range/(nbins^iters) per
    quantile — 1024^3 buckets covers float32 exactly for practical data.
    """
    valid = ~jnp.isnan(x) & (w > 0)
    xz = jnp.where(valid, x, 0.0)
    big = jnp.float32(3.4e38)

    def allred(v, op):
        return jax.lax.psum(v, axis_name) if (axis_name and op == "sum") else (
            jax.lax.pmin(v, axis_name) if (axis_name and op == "min") else (
                jax.lax.pmax(v, axis_name) if (axis_name and op == "max") else v))

    gmin = allred(jnp.min(jnp.where(valid, x, big)), "min")
    gmax = allred(jnp.max(jnp.where(valid, x, -big)), "max")
    wtot = allred(jnp.sum(jnp.where(valid, w, 0.0)), "sum")

    def hist(lo, hi):
        """Weighted histogram of values in [lo, hi) + weight below lo."""
        span = jnp.maximum(hi - lo, 1e-300)
        b = jnp.clip(((xz - lo) / span * nbins).astype(jnp.int32), 0, nbins - 1)
        inside = valid & (xz >= lo) & (xz <= hi)
        h = jax.ops.segment_sum(jnp.where(inside, w, 0.0), b, num_segments=nbins)
        below = jnp.sum(jnp.where(valid & (xz < lo), w, 0.0))
        return allred(h, "sum"), allred(below, "sum")

    out = []
    for p in probs:
        target = jnp.asarray(p, jnp.float32) * wtot
        lo, hi = gmin, gmax
        for _ in range(iters):
            h, below = hist(lo, hi)
            cum = jnp.cumsum(h) + below
            # first bin where cumulative weight reaches the target
            k = jnp.argmax(cum >= target)
            span = jnp.maximum(hi - lo, 1e-300) / nbins
            new_lo = lo + k.astype(jnp.float32) * span
            hi = new_lo + span
            lo = new_lo
        out.append((lo + hi) * 0.5)
    return jnp.stack(out)
