"""Pallas tpu_hist kernel — fused gradient-histogram accumulation.

Reference parity: this is the TPU-native equivalent of XGBoost's CUDA
`gpu_hist` updater (shared-memory atomics histogram kernel inside
`libxgboost4j_gpu.so`) and of `hex/tree/DHistogram.updateHisto`'s per-row
accumulate loop (see SURVEY.md §3.2 — the hot loop of the whole platform).

Strategy: TPUs have no scatter-atomics, so the accumulation is expressed as
a one-hot matmul that rides the MXU — but unlike the XLA-level `onehot`
path in `histogram.py`, the kernel never materializes the (rows × nodes·bins)
one-hot in HBM: each grid step builds it for one row-chunk directly in VMEM,
multiplies, and accumulates into the output block, which stays resident
across the sequential TPU grid (output-revisiting pattern). HBM traffic is
therefore just codes-in + histogram-out.

Layout: grid = (row_chunks,); per step the kernel scans features with a
fori_loop, computing hist[f, 3, L·B] += valsᵀ(3,R) @ onehot(R, L·B).

Packed-code input (ISSUE 7): the device-RESIDENT matrix is the 4/5/6-bit
`ops.packing` word matrix; `build_histograms` widens it IN-GRAPH before
these kernels, once per compiled tree program (XLA CSEs the widen across
every level's pass — only a program-lifetime transient is full-width, the
resident/cached/tunnelled artifact stays packed). In-KERNEL sub-byte
decode was evaluated and deferred: the factored kernel reads codes as
8-sublane f32 feature blocks, while Mosaic's int8 minimum tile is
(32, 128) — a u8 packed operand would force a 32-feature block
restructure (4× one-hot VMEM per step) or lane-strided unpacking of the
interleaved row groups, neither validatable without a chip in the loop.
See docs/perf.md appendix; ROADMAP items 1/3 stream the same packed
representation and inherit whichever decode lands.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pallas TPU backend is only importable on TPU builds
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PLTPU = True
except ImportError:  # pragma: no cover
    _HAVE_PLTPU = False

DEFAULT_ROW_CHUNK = 2048
FACTORED_ROW_CHUNK = 8192


_FB = 8  # features per block (TPU sublane granule)


def _hist_kernel_factored(codes_ref, node_ref, vals_ref, out_ref, w_ref,
                          *, L: int, B: int):
    """Factored VMEM kernel: grid (row_chunks, F/8), feature-blocks innermost.

    Per chunk (at fb==0) the (3L, R) node-weighted value matrix is built once
    in scratch; each step builds ONE (8B, R) bin one-hot covering its whole
    8-feature block and runs a single (3L,R)·(R,8B) MXU matmul, accumulating
    into the (1, 3L, 8B) output block. HBM traffic is codes-in + the small
    output blocks — the (R, L·B) one-hot never exists anywhere."""
    step = pl.program_id(0)
    fb = pl.program_id(1)

    @pl.when(fb == 0)
    def _weighted():
        # w[c·L+l, r] = vals[c, r] · [node[r] == l]
        l_idx = jax.lax.broadcasted_iota(jnp.int32, (3 * L, 1), 0) % L
        node = node_ref[...]                      # (1, R) i32
        mask = (node == l_idx).astype(jnp.float32)  # (3L, R)
        vals = vals_ref[...]                      # (3, R) f32
        vals3 = jnp.concatenate(
            [jnp.broadcast_to(vals[c][None, :], (L, vals.shape[1]))
             for c in range(3)], axis=0)          # (3L, R)
        w_ref[...] = vals3 * mask

    @pl.when(step == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    R = w_ref.shape[1]
    wmat = w_ref[...].astype(jnp.bfloat16)
    # one (8B, R) one-hot for the whole 8-feature block → ONE MXU matmul per
    # grid step instead of 8 tiny (3L,B) ones (output 3L × 8B utilizes the
    # systolic array far better)
    fb_iota = jax.lax.broadcasted_iota(jnp.int32, (_FB * B, R), 0)
    b_of = (fb_iota % B).astype(jnp.float32)
    codes_blk = codes_ref[...]    # (8, R) f32
    code_rows = jnp.repeat(codes_blk, B, axis=0)             # (8B, R)
    bin_oh_t = (code_rows == b_of).astype(jnp.bfloat16)      # (8B, R)
    h = jax.lax.dot_general(
        wmat, bin_oh_t,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                              # (3L, 8B)
    out_ref[0] += h


@functools.partial(jax.jit, static_argnames=("n_nodes", "nbins", "row_chunk"))
def build_histograms_pallas_factored(
    codes_t_bf: jax.Array,   # (F, N) float32 — PRE-TRANSPOSED feature-major
    node_id: jax.Array,      # (N,) int32
    vals: jax.Array,         # (3, N) f32, weight-masked
    n_nodes: int,
    nbins: int,
    row_chunk: int = FACTORED_ROW_CHUNK,
) -> jax.Array:
    """(n_nodes, F, nbins, 3) histogram; the TPU fast path for L·R fitting
    VMEM (the scratch is (3L, R) f32)."""
    if not _HAVE_PLTPU:
        raise RuntimeError("pallas TPU backend unavailable")
    F, N = codes_t_bf.shape
    L, B = n_nodes, nbins
    R = row_chunk
    npad = ((N + R - 1) // R) * R
    pad = npad - N
    Fpad = ((F + _FB - 1) // _FB) * _FB
    if pad or Fpad != F:
        # pad codes with an out-of-range bin so padded rows match no bin
        codes_t_bf = jnp.pad(codes_t_bf, ((0, Fpad - F), (0, pad)),
                             constant_values=-1.0)
        node_id = jnp.pad(node_id.astype(jnp.int32), (0, pad))
        vals = jnp.pad(vals, ((0, 0), (0, pad)))
    node2 = node_id.astype(jnp.int32)[None, :]
    grid = (npad // R, Fpad // _FB)
    out = pl.pallas_call(
        functools.partial(_hist_kernel_factored, L=L, B=B),
        out_shape=jax.ShapeDtypeStruct((Fpad // _FB, 3 * L, _FB * B), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_FB, R), lambda i, f: (f, i)),  # codes_t chunk
            pl.BlockSpec((1, R), lambda i, f: (0, i)),    # node chunk
            pl.BlockSpec((3, R), lambda i, f: (0, i)),    # vals chunk
        ],
        out_specs=pl.BlockSpec((1, 3 * L, _FB * B), lambda i, f: (f, 0, 0)),
        scratch_shapes=[pltpu.VMEM((3 * L, R), jnp.float32)],
    )(codes_t_bf, node2, vals)
    # (Fpad/8, 3L, 8B) → (Fpad, 3L, B) → (L, F, B, 3)
    out = out.reshape(Fpad // _FB, 3 * L, _FB, B).transpose(0, 2, 1, 3)
    out = out.reshape(Fpad, 3 * L, B)[:F]
    return out.reshape(F, 3, L, B).transpose(2, 0, 3, 1)


def _hist_kernel(codes_ref, cid_base_ref, vals_ref, out_ref, *, F: int, LB: int):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    vals = vals_ref[...]                       # (3, R) f32
    base = cid_base_ref[...]                   # (1, R) i32 = node*B
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, LB), 1)

    def body(f, _):
        code_f = codes_ref[f, :]               # (R,) i32
        cid = base[0, :] + code_f              # (R,)
        onehot = (cid[:, None] == iota).astype(jnp.bfloat16)      # (R, LB)
        part = jax.lax.dot_general(
            vals.astype(jnp.bfloat16), onehot,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                       # (3, LB)
        out_ref[f, :, :] += part
        return 0

    jax.lax.fori_loop(0, F, body, 0)


@functools.partial(jax.jit, static_argnames=("n_nodes", "nbins", "row_chunk"))
def build_histograms_pallas(
    codes: jax.Array,      # (N, F) any int dtype
    node_id: jax.Array,    # (N,) int32
    vals: jax.Array,       # (3, N) f32 — rows already weight-masked
    n_nodes: int,
    nbins: int,
    row_chunk: int = DEFAULT_ROW_CHUNK,
) -> jax.Array:
    """(n_nodes, F, nbins, 3) histogram via the fused pallas kernel."""
    if not _HAVE_PLTPU:
        raise RuntimeError("pallas TPU backend unavailable")
    N, F = codes.shape
    LB = n_nodes * nbins
    R = row_chunk
    npad = ((N + R - 1) // R) * R
    pad = npad - N
    codes_i = codes.astype(jnp.int32)
    if pad:
        codes_i = jnp.pad(codes_i, ((0, pad), (0, 0)))
        node_id = jnp.pad(node_id.astype(jnp.int32), (0, pad))
        vals = jnp.pad(vals, ((0, 0), (0, pad)))  # zero vals ⇒ no contribution
    cid_base = (node_id.astype(jnp.int32) * nbins)[None, :]  # (1, npad)
    codes_t = codes_i.T  # (F, npad) — feature-major so each chunk is contiguous

    grid = (npad // R,)
    out = pl.pallas_call(
        functools.partial(_hist_kernel, F=F, LB=LB),
        out_shape=jax.ShapeDtypeStruct((F, 3, LB), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((F, R), lambda i: (0, i)),      # codes_t chunk
            pl.BlockSpec((1, R), lambda i: (0, i)),      # cid_base chunk
            pl.BlockSpec((3, R), lambda i: (0, i)),      # vals chunk
        ],
        out_specs=pl.BlockSpec((F, 3, LB), lambda i: (0, 0, 0)),
    )(codes_t, cid_base, vals)
    # (F, 3, LB) → (n_nodes, F, nbins, 3)
    return out.reshape(F, 3, n_nodes, nbins).transpose(2, 0, 3, 1)
