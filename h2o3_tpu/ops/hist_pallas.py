"""Pallas tpu_hist kernel — fused gradient-histogram accumulation.

Reference parity: this is the TPU-native equivalent of XGBoost's CUDA
`gpu_hist` updater (shared-memory atomics histogram kernel inside
`libxgboost4j_gpu.so`) and of `hex/tree/DHistogram.updateHisto`'s per-row
accumulate loop (see SURVEY.md §3.2 — the hot loop of the whole platform).

Strategy: TPUs have no scatter-atomics, so the accumulation is expressed as
a one-hot matmul that rides the MXU — but unlike the XLA-level `onehot`
path in `histogram.py`, the kernel never materializes the (rows × nodes·bins)
one-hot in HBM: each grid step builds it for one row-chunk directly in VMEM,
multiplies, and accumulates into the output block, which stays resident
across the sequential TPU grid (output-revisiting pattern). HBM traffic is
therefore just codes-in + histogram-out.

Layout: grid = (row_chunks,); per step the kernel scans features with a
fori_loop, computing hist[f, 3, L·B] += valsᵀ(3,R) @ onehot(R, L·B).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pallas TPU backend is only importable on TPU builds
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PLTPU = True
except ImportError:  # pragma: no cover
    _HAVE_PLTPU = False

DEFAULT_ROW_CHUNK = 2048


def _hist_kernel(codes_ref, cid_base_ref, vals_ref, out_ref, *, F: int, LB: int):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    vals = vals_ref[...]                       # (3, R) f32
    base = cid_base_ref[...]                   # (1, R) i32 = node*B
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, LB), 1)

    def body(f, _):
        code_f = codes_ref[f, :]               # (R,) i32
        cid = base[0, :] + code_f              # (R,)
        onehot = (cid[:, None] == iota).astype(jnp.bfloat16)      # (R, LB)
        part = jax.lax.dot_general(
            vals.astype(jnp.bfloat16), onehot,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                       # (3, LB)
        out_ref[f, :, :] += part
        return 0

    jax.lax.fori_loop(0, F, body, 0)


@functools.partial(jax.jit, static_argnames=("n_nodes", "nbins", "row_chunk"))
def build_histograms_pallas(
    codes: jax.Array,      # (N, F) any int dtype
    node_id: jax.Array,    # (N,) int32
    vals: jax.Array,       # (3, N) f32 — rows already weight-masked
    n_nodes: int,
    nbins: int,
    row_chunk: int = DEFAULT_ROW_CHUNK,
) -> jax.Array:
    """(n_nodes, F, nbins, 3) histogram via the fused pallas kernel."""
    if not _HAVE_PLTPU:
        raise RuntimeError("pallas TPU backend unavailable")
    N, F = codes.shape
    LB = n_nodes * nbins
    R = row_chunk
    npad = ((N + R - 1) // R) * R
    pad = npad - N
    codes_i = codes.astype(jnp.int32)
    if pad:
        codes_i = jnp.pad(codes_i, ((0, pad), (0, 0)))
        node_id = jnp.pad(node_id.astype(jnp.int32), (0, pad))
        vals = jnp.pad(vals, ((0, 0), (0, pad)))  # zero vals ⇒ no contribution
    cid_base = (node_id.astype(jnp.int32) * nbins)[None, :]  # (1, npad)
    codes_t = codes_i.T  # (F, npad) — feature-major so each chunk is contiguous

    grid = (npad // R,)
    out = pl.pallas_call(
        functools.partial(_hist_kernel, F=F, LB=LB),
        out_shape=jax.ShapeDtypeStruct((F, 3, LB), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((F, R), lambda i: (0, i)),      # codes_t chunk
            pl.BlockSpec((1, R), lambda i: (0, i)),      # cid_base chunk
            pl.BlockSpec((3, R), lambda i: (0, i)),      # vals chunk
        ],
        out_specs=pl.BlockSpec((F, 3, LB), lambda i: (0, 0, 0)),
    )(codes_t, cid_base, vals)
    # (F, 3, LB) → (n_nodes, F, nbins, 3)
    return out.reshape(F, 3, n_nodes, nbins).transpose(2, 0, 3, 1)
