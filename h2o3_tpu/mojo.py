"""Model artifacts — save/load + standalone scoring.

Reference parity: `h2o-genmodel/src/main/java/hex/genmodel/` (`MojoModel`,
`MojoReaderBackend`, `easy/EasyPredictModelWrapper`) and the in-cluster
binary save (`h2o.save_model` → `/3/Models.bin`, Iced serialization of the
model). The MOJO design — a zip of `model.ini` metadata + binary arrays,
scoreable with zero h2o-core dependency — maps here to an `.npz` bundle of
(params json + numpy arrays); `MojoScorer` below scores GBM/DRF/GLM/DL
artifacts with numpy only (no JAX import needed at serve time).
"""

from __future__ import annotations

import io
import json
import os
import zipfile
from typing import Any, Dict, List, Optional

import numpy as np

FORMAT_VERSION = 1


def _model_payload(model) -> Dict[str, Any]:
    """Extract (meta, arrays) from a trained H2OModel."""
    from .models.shared_tree import SharedTreeModel
    from .models.glm import GLMModel
    from .models.deeplearning import DeepLearningModel

    arrays: Dict[str, np.ndarray] = {}
    meta: Dict[str, Any] = {
        "format_version": FORMAT_VERSION,
        "model_id": model.model_id,
        "algo": model.algo,
        "x": model.x,
        "y": model.y,
    }
    if isinstance(model, SharedTreeModel):
        meta.update(
            kind="tree", problem=model.problem, nclass=model.nclass,
            domain=model.domain, distribution=model.distribution,
            max_depth=model.max_depth, mode=model.mode,
            ntrees=model.ntrees_built,
            f0=np.asarray(model.f0).tolist(),
            feature_domains=model.bm.domains,
        )
        for k, stacked in enumerate(model.forest):
            for field in ("feat", "bin", "thr", "is_split", "value"):
                arrays[f"forest{k}_{field}"] = np.asarray(getattr(stacked, field))
            covers = getattr(model, "covers", None)
            if covers:
                # per-node training covers — predict_contributions (TreeSHAP)
                arrays[f"forest{k}_cover"] = np.asarray(covers[k], np.float32)
        meta["n_forests"] = len(model.forest)
    elif isinstance(model, GLMModel):
        meta.update(
            kind="glm", family=model.family, domain=model.domain,
            coef_names=model._names(), standardize=model.dinfo.standardize,
        )
        arrays["beta"] = np.asarray(model.beta)
        if model.dinfo.means is not None:
            arrays["means"] = model.dinfo.means
            arrays["stds"] = model.dinfo.stds
        meta["dinfo"] = _dinfo_meta(model.dinfo)
    elif isinstance(model, DeepLearningModel):
        meta.update(
            kind="deeplearning", problem=model.problem, nclass=model.nclass,
            domain=model.domain, activation=model.activation,
            distribution=model.distribution, n_layers=len(model.net_params),
        )
        for i, (W, b) in enumerate(model.net_params):
            arrays[f"W{i}"] = np.asarray(W)
            arrays[f"b{i}"] = np.asarray(b)
        if model.dinfo.means is not None:
            arrays["means"] = model.dinfo.means
            arrays["stds"] = model.dinfo.stds
        meta["dinfo"] = _dinfo_meta(model.dinfo)
    else:
        from .models.isolation_forest import IsolationForestModel
        from .models.kmeans import KMeansModel
        from .models.pca import PCAModel

        if isinstance(model, IsolationForestModel):
            meta.update(kind="isoforest", sample_size=model.sample_size,
                        max_depth=model.max_depth, ntrees=len(model.trees))
            arrays["if_feat"] = np.stack([t[0] for t in model.trees]).astype(np.int32)
            arrays["if_thr"] = np.stack([t[1] for t in model.trees]).astype(np.float32)
            arrays["if_split"] = np.stack([t[2] for t in model.trees])
            arrays["if_leafn"] = np.stack([t[3] for t in model.trees]).astype(np.float64)
        elif isinstance(model, KMeansModel):
            meta.update(kind="kmeans", k=model.k)
            arrays["centers_std"] = np.asarray(model.centers_std)
            if model.dinfo.means is not None:
                arrays["means"] = model.dinfo.means
                arrays["stds"] = model.dinfo.stds
            meta["dinfo"] = _dinfo_meta(model.dinfo)
        elif isinstance(model, PCAModel):
            meta.update(kind="pca", k=model.k)
            arrays["eigenvectors"] = np.asarray(model.eigenvectors)
            arrays["eigenvalues"] = np.asarray(model.eigenvalues)
            if model.dinfo.means is not None:
                arrays["means"] = model.dinfo.means
                arrays["stds"] = model.dinfo.stds
            meta["dinfo"] = _dinfo_meta(model.dinfo)
        else:
            raise TypeError(f"cannot export model of type {type(model).__name__}")
    return {"meta": meta, "arrays": arrays}


def _dinfo_meta(dinfo) -> Dict:
    return {
        "spec": [[k, n, d] for (k, n, d) in dinfo._spec],
        "coef_names": dinfo.coef_names,
        "standardize": dinfo.standardize,
        "use_all": dinfo.use_all,
        "col_means": dinfo.col_means,
    }


def save_model(est_or_model, path: str = ".", filename: Optional[str] = None,
               force: bool = False) -> str:
    model = getattr(est_or_model, "model", est_or_model)
    payload = _model_payload(model)
    os.makedirs(path, exist_ok=True) if not os.path.splitext(path)[1] else None
    if os.path.isdir(path) or not os.path.splitext(path)[1]:
        fn = filename or f"{model.model_id}.h2o3"
        out = os.path.join(path, fn)
    else:
        out = path
    if os.path.exists(out) and not force:
        raise FileExistsError(f"{out} exists; pass force=True")
    with zipfile.ZipFile(out, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("model.json", json.dumps(payload["meta"]))
        buf = io.BytesIO()
        np.savez(buf, **payload["arrays"])
        z.writestr("arrays.npz", buf.getvalue())
    return out


def load_model(path: str) -> "MojoScorer":
    with zipfile.ZipFile(path) as z:
        meta = json.loads(z.read("model.json"))
        arrays = dict(np.load(io.BytesIO(z.read("arrays.npz"))))
    return MojoScorer(meta, arrays)


class MojoScorer:
    """Numpy-only offline scorer — `EasyPredictModelWrapper` equivalent.

    predict() accepts a Frame or a numpy matrix in training-column order and
    returns the same columns the in-cluster scorer produces."""

    def __init__(self, meta: Dict, arrays: Dict[str, np.ndarray]):
        self.meta = meta
        self.arrays = arrays
        self.algo = meta["algo"]
        self.x = meta["x"]
        self.y = meta["y"]
        self._native_forests: Dict[int, tuple] = {}  # k → converted arrays

    def _native_forest(self, k: int):
        """Contiguous ctypes-ready forest arrays, converted once per class
        (the serving hot path must not re-copy the model every call)."""
        if k not in self._native_forests:
            self._native_forests[k] = (
                np.ascontiguousarray(self.arrays[f"forest{k}_feat"], np.int32),
                np.ascontiguousarray(self.arrays[f"forest{k}_thr"], np.float32),
                np.ascontiguousarray(self.arrays[f"forest{k}_is_split"]).astype(np.uint8),
                np.ascontiguousarray(self.arrays[f"forest{k}_value"], np.float32),
            )
        return self._native_forests[k]

    # -- shared helpers -----------------------------------------------------
    def _matrix(self, data) -> np.ndarray:
        from .frame.frame import Frame

        if isinstance(data, Frame):
            from .models.shared_tree import frame_to_matrix

            X, _, _ = frame_to_matrix(
                data, self.x, expected_domains=self.meta.get("feature_domains")
            )
            return X
        return np.asarray(data, np.float64)

    def _tree_scores(self, X: np.ndarray) -> np.ndarray:
        from .native import loader as native_loader

        meta = self.meta
        D = meta["max_depth"]
        outs = []
        for k in range(meta["n_forests"]):
            feat, thr, split, value = self._native_forest(k)
            # native C++ traversal (mojo_scorer.cpp) — numpy fallback below
            total = native_loader.score_forest(feat, thr, split, value, D, X)
            if total is None:
                ntrees = feat.shape[0]
                total = np.zeros(X.shape[0])
                for t in range(ntrees):
                    node = np.zeros(X.shape[0], np.int64)
                    for _ in range(D):
                        f = feat[t][node]
                        s = split[t][node]
                        xv = X[np.arange(X.shape[0]), f]
                        right = np.isnan(xv) | (xv > thr[t][node])
                        child = 2 * node + 1 + (right & s).astype(np.int64)
                        node = np.where(s, child, node)
                    total += value[t][node]
            f0 = meta["f0"]
            f0k = f0[k] if isinstance(f0, list) else f0
            outs.append(total + (f0k if meta["mode"] != "drf" else 0.0))
        return np.column_stack(outs)

    def _expand_dinfo(self, data) -> np.ndarray:
        from .frame.frame import Frame

        di = self.meta["dinfo"]
        cols = []
        for kind, n, dom in di["spec"]:
            if isinstance(data, Frame):
                v = data.vec(n)
                raw = v.numeric_np()
                codes = np.asarray(v.data) if v.type == "enum" else None
                vdom = v.domain
            else:
                raise TypeError("dinfo models require a Frame input")
            if kind == "num":
                c = np.where(np.isnan(raw), di["col_means"].get(n, 0.0), raw)
                cols.append(c[:, None])
            else:
                if vdom != dom and vdom:
                    remap = np.asarray(
                        [dom.index(d) if d in dom else -1 for d in vdom], np.int64
                    )
                    codes = np.where(codes >= 0, remap[np.maximum(codes, 0)], -1)
                K = len(dom)
                oh = np.zeros((len(codes), K))
                valid = codes >= 0
                oh[np.nonzero(valid)[0], codes[valid]] = 1.0
                if not di["use_all"] and K > 0:
                    oh = oh[:, 1:]
                cols.append(oh)
        X = np.concatenate(cols, axis=1)
        if di["standardize"] and "means" in self.arrays:
            X = (X - self.arrays["means"]) / self.arrays["stds"]
        return np.nan_to_num(X, nan=0.0)

    def predict_contributions(self, data):
        """Offline SHAP contributions + BiasTerm — the genmodel-side
        `predictContributions` (hex/genmodel/algos/tree/TreeSHAP.java via
        EasyPredictModelWrapper). Tree artifacts with recorded covers only;
        binomial/regression, as in-cluster."""
        from .frame.frame import Frame

        meta = self.meta
        if meta["kind"] != "tree":
            raise ValueError("predict_contributions requires a tree artifact")
        if meta["problem"] == "multinomial":
            raise ValueError("predict_contributions is not supported for "
                             "multinomial models")
        if "forest0_cover" not in self.arrays:
            raise ValueError("artifact has no node covers (exported before "
                             "TreeSHAP support); re-export the model")
        from .models.tree_shap import compute_contributions

        X = self._matrix(data)
        feat, thr, split, value = self._native_forest(0)
        cover = np.ascontiguousarray(self.arrays["forest0_cover"], np.float32)
        scale = 1.0 / max(meta["ntrees"], 1) if meta["mode"] == "drf" else 1.0
        f0 = meta["f0"]
        f0k = f0[0] if isinstance(f0, list) else f0
        contrib = compute_contributions(feat, thr, split, value, cover, X,
                                        scale, f0k)
        names = list(self.x) + ["BiasTerm"]
        return Frame.from_dict({n: contrib[:, j] for j, n in enumerate(names)})

    # -- prediction ---------------------------------------------------------
    def predict(self, data):
        from .frame.frame import Frame

        meta = self.meta
        kind = meta["kind"]
        if kind == "tree":
            X = self._matrix(data)
            m = self._tree_scores(X)
            problem = meta["problem"]
            if meta["mode"] == "drf":
                m = m / max(meta["ntrees"], 1)
                if problem == "binomial":
                    p1 = np.clip(m[:, 0], 0, 1)
                    probs = np.column_stack([1 - p1, p1])
                elif problem == "multinomial":
                    p = np.clip(m, 0, None)
                    probs = p / np.maximum(p.sum(axis=1, keepdims=True), 1e-12)
                else:
                    return Frame.from_dict({"predict": m[:, 0]})
            else:
                if problem == "binomial":
                    p1 = 1 / (1 + np.exp(-m[:, 0]))
                    probs = np.column_stack([1 - p1, p1])
                elif problem == "multinomial":
                    e = np.exp(m - m.max(axis=1, keepdims=True))
                    probs = e / e.sum(axis=1, keepdims=True)
                else:
                    out = m[:, 0]
                    if meta["distribution"] in ("poisson", "gamma", "tweedie"):
                        out = np.exp(out)
                    return Frame.from_dict({"predict": out})
            dom = meta["domain"]
            d = {"predict": np.asarray(dom, dtype=object)[probs.argmax(axis=1)]}
            for i, cls in enumerate(dom):
                d[str(cls)] = probs[:, i]
            return Frame.from_dict(d, column_types={"predict": "enum"})
        if kind == "glm":
            X = self._expand_dinfo(data)
            Xi = np.concatenate([X, np.ones((X.shape[0], 1))], axis=1)
            beta = self.arrays["beta"]
            eta = Xi @ beta.T
            fam = meta["family"]
            if fam in ("binomial", "quasibinomial", "fractionalbinomial"):
                p1 = 1 / (1 + np.exp(-np.clip(eta, -500, 500)))
                dom = meta["domain"]
                return Frame.from_dict({
                    "predict": np.asarray(dom, dtype=object)[(p1 > 0.5).astype(int)],
                    str(dom[0]): 1 - p1, str(dom[1]): p1,
                }, column_types={"predict": "enum"})
            if fam == "multinomial":
                e = np.exp(eta - eta.max(axis=1, keepdims=True))
                probs = e / e.sum(axis=1, keepdims=True)
                dom = meta["domain"]
                d = {"predict": np.asarray(dom, dtype=object)[probs.argmax(axis=1)]}
                for i, cls in enumerate(dom):
                    d[str(cls)] = probs[:, i]
                return Frame.from_dict(d, column_types={"predict": "enum"})
            if fam in ("poisson", "gamma", "tweedie"):
                eta = np.exp(eta)
            return Frame.from_dict({"predict": eta})
        if kind == "isoforest":
            from .models.isolation_forest import anomaly_scores, forest_path_lengths

            X = self._matrix(data)
            trees = zip(self.arrays["if_feat"], self.arrays["if_thr"],
                        self.arrays["if_split"], self.arrays["if_leafn"])
            pl = forest_path_lengths(trees, X, self.meta["max_depth"])
            score = anomaly_scores(pl, self.meta["sample_size"])
            return Frame.from_dict({"predict": score, "mean_length": pl})
        if kind == "kmeans":
            X = self._expand_dinfo(data)
            c = self.arrays["centers_std"]
            d2 = (np.sum(X * X, axis=1, keepdims=True) - 2.0 * X @ c.T
                  + np.sum(c * c, axis=1)[None, :])
            return Frame.from_dict({"predict": d2.argmin(axis=1).astype(np.float64)})
        if kind == "pca":
            X = self._expand_dinfo(data)
            scores = X @ self.arrays["eigenvectors"]
            return Frame.from_dict(
                {f"PC{i+1}": scores[:, i] for i in range(self.meta["k"])})
        if kind == "deeplearning":
            X = self._expand_dinfo(data)
            h = X
            L = meta["n_layers"]
            act = meta["activation"].replace("WithDropout", "")
            for i in range(L):
                z = h @ self.arrays[f"W{i}"] + self.arrays[f"b{i}"]
                if i < L - 1:
                    if act == "Rectifier":
                        h = np.maximum(z, 0)
                    elif act == "Tanh":
                        h = np.tanh(z)
                    else:  # Maxout
                        h = z.reshape(z.shape[0], -1, 2).max(axis=2)
                else:
                    h = z
            problem = meta["problem"]
            if problem in ("binomial", "multinomial"):
                e = np.exp(h - h.max(axis=1, keepdims=True))
                probs = e / e.sum(axis=1, keepdims=True)
                dom = meta["domain"]
                d = {"predict": np.asarray(dom, dtype=object)[probs.argmax(axis=1)]}
                for i, cls in enumerate(dom):
                    d[str(cls)] = probs[:, i]
                return Frame.from_dict(d, column_types={"predict": "enum"})
            out = h[:, 0]
            if meta["distribution"] in ("poisson", "gamma", "tweedie"):
                out = np.exp(out)
            return Frame.from_dict({"predict": out})
        raise ValueError(f"unknown artifact kind {kind!r}")
