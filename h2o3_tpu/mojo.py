"""Model artifacts — save/load + standalone scoring.

Reference parity: `h2o-genmodel/src/main/java/hex/genmodel/` (`MojoModel`,
`MojoReaderBackend`, `easy/EasyPredictModelWrapper`) and the in-cluster
binary save (`h2o.save_model` → `/3/Models.bin`, Iced serialization of the
model). The MOJO design — a zip of `model.ini` metadata + binary arrays,
scoreable with zero h2o-core dependency — maps here to an `.npz` bundle of
(params json + numpy arrays); `MojoScorer` below scores GBM/DRF/GLM/DL
artifacts with numpy only (no JAX import needed at serve time).
"""

from __future__ import annotations

import io
import json
import os
import zipfile
from typing import Any, Dict, List, Optional

import numpy as np

FORMAT_VERSION = 1


def _model_payload(model) -> Dict[str, Any]:
    """Extract (meta, arrays) from a trained H2OModel."""
    from .models.shared_tree import SharedTreeModel
    from .models.glm import GLMModel
    from .models.deeplearning import DeepLearningModel

    if isinstance(model, MojoScorer):
        # a loaded artifact re-exports losslessly (upload→download
        # round-trip on a serving cluster): its payload IS its state
        out: Dict[str, Any] = {"meta": dict(model.meta),
                               "arrays": dict(model.arrays)}
        if model.children:
            out["children"] = {k: _model_payload(c)
                               for k, c in model.children.items()}
        return out
    arrays: Dict[str, np.ndarray] = {}
    meta: Dict[str, Any] = {
        "format_version": FORMAT_VERSION,
        "model_id": model.model_id,
        "algo": model.algo,
        "x": model.x,
        "y": model.y,
    }
    if isinstance(model, SharedTreeModel):
        meta.update(
            kind="tree", problem=model.problem, nclass=model.nclass,
            domain=model.domain, distribution=model.distribution,
            max_depth=model.max_depth, mode=model.mode,
            ntrees=model.ntrees_built,
            f0=np.asarray(model.f0).tolist(),
            feature_domains=model.bm.domains,
        )
        for k, stacked in enumerate(model.forest):
            for field in ("feat", "bin", "thr", "is_split", "value"):
                arrays[f"forest{k}_{field}"] = np.asarray(getattr(stacked, field))
            covers = getattr(model, "covers", None)
            if covers:
                # per-node training covers — predict_contributions (TreeSHAP)
                arrays[f"forest{k}_cover"] = np.asarray(covers[k], np.float32)
        meta["n_forests"] = len(model.forest)
    elif isinstance(model, GLMModel):
        meta.update(
            kind="glm", family=model.family, domain=model.domain,
            coef_names=model._names(), standardize=model.dinfo.standardize,
        )
        arrays["beta"] = np.asarray(model.beta)
        if model.dinfo.means is not None:
            arrays["means"] = model.dinfo.means
            arrays["stds"] = model.dinfo.stds
        meta["dinfo"] = _dinfo_meta(model.dinfo)
    elif isinstance(model, DeepLearningModel):
        meta.update(
            kind="deeplearning", problem=model.problem, nclass=model.nclass,
            domain=model.domain, activation=model.activation,
            distribution=model.distribution, n_layers=len(model.net_params),
        )
        for i, (W, b) in enumerate(model.net_params):
            arrays[f"W{i}"] = np.asarray(W)
            arrays[f"b{i}"] = np.asarray(b)
        if model.dinfo.means is not None:
            arrays["means"] = model.dinfo.means
            arrays["stds"] = model.dinfo.stds
        meta["dinfo"] = _dinfo_meta(model.dinfo)
    else:
        from .models.isolation_forest import IsolationForestModel
        from .models.kmeans import KMeansModel
        from .models.pca import PCAModel
        from .models.extended_isolation_forest import \
            ExtendedIsolationForestModel
        from .models.ensemble import StackedEnsembleModel
        from .models.word2vec import Word2VecModel
        from .models.glrm import GLRMModel
        from .models.targetencoder import TargetEncoderModel
        from .models.rulefit import RuleFitModel
        from .models.coxph import CoxPHModel
        from .models.naive_bayes import NaiveBayesModel
        from .models.isotonic import IsotonicRegressionModel
        from .models.svd import SVDModel

        if isinstance(model, ExtendedIsolationForestModel):
            meta.update(kind="eif", depth=model.depth,
                        sample_size=model.sample_size,
                        dinfo=_dinfo_meta(model.dinfo))
            arrays["eif_dirs"] = np.asarray(model.dirs, np.float32)
            arrays["eif_thrs"] = np.asarray(model.thrs, np.float32)
            arrays["eif_splits"] = np.asarray(model.splits, bool)
            arrays["eif_counts"] = np.asarray(model.counts, np.float64)
        elif isinstance(model, StackedEnsembleModel):
            # recursive artifact: every base model + the metalearner ride
            # along as child payloads (hex/genmodel StackedEnsembleMojoModel)
            meta.update(kind="stackedensemble", problem=model.problem,
                        nclass=model.nclass, domain=model.domain,
                        n_base=len(model.base_models))
            children = {
                f"base{i}": _model_payload(bm.model)
                for i, bm in enumerate(model.base_models)
            }
            children["meta"] = _model_payload(model.meta.model)
            return {"meta": meta, "arrays": arrays, "children": children}
        elif isinstance(model, Word2VecModel):
            meta.update(kind="word2vec", dim=int(model.vectors.shape[1]))
            arrays["w2v_vectors"] = np.asarray(model.vectors, np.float32)
            arrays["w2v_vocab"] = np.asarray(model.vocab, dtype="U")
        elif isinstance(model, GLRMModel):
            meta.update(kind="glrm", k=model.k,
                        dinfo=_dinfo_meta(model.dinfo))
            arrays["glrm_y"] = np.asarray(model.Y, np.float64)
            if model.dinfo.means is not None:
                arrays["means"] = model.dinfo.means
                arrays["stds"] = model.dinfo.stds
        elif isinstance(model, TargetEncoderModel):
            te_cols = []
            for i, (col, (dom, sums, cnts, _folds)) in enumerate(
                    model.encodings.items()):
                te_cols.append({"col": col, "domain": list(dom)})
                arrays[f"te{i}_sums"] = np.asarray(sums, np.float64)
                arrays[f"te{i}_cnts"] = np.asarray(cnts, np.float64)
            meta.update(kind="targetencoder", te_cols=te_cols,
                        prior=float(model.prior),
                        blending=bool(model.blending),
                        te_k=float(model.k), te_f=float(model.f))
        elif isinstance(model, RuleFitModel):
            meta.update(
                kind="rulefit",
                rules=[[[str(f), float(t), bool(rt)] for (f, t, rt) in r.conds]
                       for r in model.rules],
                lin_cols=list(model.lin_cols),
                lin_stats={c: [float(v) for v in model.lin_stats[c]]
                           for c in model.lin_cols},
            )
            return {"meta": meta, "arrays": arrays,
                    "children": {"glm": _model_payload(model._glm.model)}}
        elif isinstance(model, CoxPHModel):
            meta.update(kind="coxph", dinfo=_dinfo_meta(model.dinfo))
            arrays["beta"] = np.asarray(model.beta, np.float64)
            if model.dinfo.means is not None:
                arrays["means"] = model.dinfo.means
                arrays["stds"] = model.dinfo.stds
        elif isinstance(model, NaiveBayesModel):
            nb_spec = []
            for name, knd in model.spec:
                ent = {"name": name, "kind": knd}
                if knd == "num":
                    arrays[f"nb_num_{name}"] = np.asarray(
                        model.num_stats[name], np.float64)
                else:
                    probs, dom = model.cat_tables[name]
                    ent["domain"] = list(dom)
                    arrays[f"nb_cat_{name}"] = np.asarray(probs, np.float64)
                nb_spec.append(ent)
            meta.update(kind="naivebayes", domain=model.domain,
                        nb_spec=nb_spec)
            arrays["nb_priors"] = np.asarray(model.priors, np.float64)
        elif isinstance(model, IsotonicRegressionModel):
            meta.update(kind="isotonic", out_of_bounds=model.out_of_bounds)
            arrays["iso_tx"] = np.asarray(model.thresholds_x, np.float64)
            arrays["iso_ty"] = np.asarray(model.thresholds_y, np.float64)
        elif isinstance(model, SVDModel):
            meta.update(kind="svd", dinfo=_dinfo_meta(model.dinfo))
            arrays["svd_d"] = np.asarray(model.d, np.float64)
            arrays["svd_v"] = np.asarray(model.v, np.float64)
            if model.dinfo.means is not None:
                arrays["means"] = model.dinfo.means
                arrays["stds"] = model.dinfo.stds
        elif isinstance(model, IsolationForestModel):
            meta.update(kind="isoforest", sample_size=model.sample_size,
                        max_depth=model.max_depth, ntrees=len(model.trees))
            arrays["if_feat"] = np.stack([t[0] for t in model.trees]).astype(np.int32)
            arrays["if_thr"] = np.stack([t[1] for t in model.trees]).astype(np.float32)
            arrays["if_split"] = np.stack([t[2] for t in model.trees])
            arrays["if_leafn"] = np.stack([t[3] for t in model.trees]).astype(np.float64)
        elif isinstance(model, KMeansModel):
            meta.update(kind="kmeans", k=model.k)
            arrays["centers_std"] = np.asarray(model.centers_std)
            if model.dinfo.means is not None:
                arrays["means"] = model.dinfo.means
                arrays["stds"] = model.dinfo.stds
            meta["dinfo"] = _dinfo_meta(model.dinfo)
        elif isinstance(model, PCAModel):
            meta.update(kind="pca", k=model.k)
            arrays["eigenvectors"] = np.asarray(model.eigenvectors)
            arrays["eigenvalues"] = np.asarray(model.eigenvalues)
            if model.dinfo.means is not None:
                arrays["means"] = model.dinfo.means
                arrays["stds"] = model.dinfo.stds
            meta["dinfo"] = _dinfo_meta(model.dinfo)
        elif _is_gam(model):
            # first-class GAM artifact (hex/genmodel/algos/gam/): the spline
            # basis (knots + centering) rides along, so offline predict ≡
            # in-cluster on NEW data — not just the inner GLM
            meta.update(kind="gam", family=model.family, domain=model.domain,
                        dinfo=_dinfo_meta(model.dinfo),
                        gam_cols=[c for c, _, _ in model.gam_spec])
            arrays["beta"] = np.asarray(model.beta, np.float64)
            for i, (_col, knots, center) in enumerate(model.gam_spec):
                arrays[f"gam{i}_knots"] = np.asarray(knots, np.float64)
                arrays[f"gam{i}_center"] = np.asarray(center, np.float64)
            if model.dinfo.means is not None:
                arrays["means"] = model.dinfo.means
                arrays["stds"] = model.dinfo.stds
        elif _is_uplift(model):
            # UpliftDRF artifact (upstream genmodel gained uplift scoring):
            # one forest whose leaves hold treatment−control differences
            meta.update(kind="uplift", max_depth=model.max_depth,
                        ntrees=model.ntrees_built,
                        feature_domains=model.bm.domains,
                        treatment_col=model.treatment_col)
            for field in ("feat", "bin", "thr", "is_split", "value"):
                arrays[f"uforest_{field}"] = np.asarray(
                    getattr(model.forest, field))
        else:
            # Ratified cuts (documented in README "Intentional cuts" +
            # docs/mojo.md): Aggregator (produces a frame, no row scorer),
            # PSVM, ANOVAGLM/ModelSelection (in-cluster scoring only for
            # now) — every other predict()-bearing model kind exports.
            raise TypeError(
                f"cannot export model of type {type(model).__name__}: "
                "not a MOJO-exportable kind (see docs/mojo.md for the "
                "export matrix and ratified cuts)")
    return {"meta": meta, "arrays": arrays}


def _is_gam(model) -> bool:
    from .models.gam import GAMModel

    return isinstance(model, GAMModel)


def _is_uplift(model) -> bool:
    from .models.uplift import UpliftRandomForestModel

    return isinstance(model, UpliftRandomForestModel)


def _dinfo_meta(dinfo) -> Dict:
    return {
        "spec": [[k, n, d] for (k, n, d) in dinfo._spec],
        "coef_names": dinfo.coef_names,
        "standardize": dinfo.standardize,
        "use_all": dinfo.use_all,
        "col_means": dinfo.col_means,
    }


def save_model(est_or_model, path: str = ".", filename: Optional[str] = None,
               force: bool = False) -> str:
    model = getattr(est_or_model, "model", est_or_model)
    payload = _model_payload(model)
    os.makedirs(path, exist_ok=True) if not os.path.splitext(path)[1] else None
    if os.path.isdir(path) or not os.path.splitext(path)[1]:
        fn = filename or f"{model.model_id}.h2o3"
        out = os.path.join(path, fn)
    else:
        out = path
    if os.path.exists(out) and not force:
        raise FileExistsError(f"{out} exists; pass force=True")
    with zipfile.ZipFile(out, "w", zipfile.ZIP_DEFLATED) as z:
        _write_payload(z, "", payload)
    return out


def _write_payload(z: "zipfile.ZipFile", prefix: str, payload: Dict) -> None:
    """One payload (meta + arrays [+ children, recursively]) under a zip
    prefix — the nested-directory MOJO convention (`models/` sub-entries in
    hex/genmodel StackedEnsembleMojoModel)."""
    z.writestr(prefix + "model.json", json.dumps(payload["meta"]))
    buf = io.BytesIO()
    np.savez(buf, **payload["arrays"])
    z.writestr(prefix + "arrays.npz", buf.getvalue())
    for name, child in (payload.get("children") or {}).items():
        _write_payload(z, f"{prefix}{name}/", child)


def _read_payload(z: "zipfile.ZipFile", prefix: str,
                  names: List[str]) -> "MojoScorer":
    meta = json.loads(z.read(prefix + "model.json"))
    arrays = dict(np.load(io.BytesIO(z.read(prefix + "arrays.npz"))))
    kids = sorted({
        n[len(prefix):].split("/", 1)[0]
        for n in names
        if n.startswith(prefix) and "/" in n[len(prefix):]
    })
    children = {k: _read_payload(z, f"{prefix}{k}/", names) for k in kids}
    return MojoScorer(meta, arrays, children=children or None)


def load_model(path: str) -> "MojoScorer":
    with zipfile.ZipFile(path) as z:
        return _read_payload(z, "", z.namelist())


def _remap_codes(codes: np.ndarray, vdom, dom) -> np.ndarray:
    """Align enum codes from a scoring frame's domain to the stored
    training domain (-1 = unseen level) — one implementation for every
    scorer kind."""
    if vdom != dom and vdom:
        lookup = {d: i for i, d in enumerate(dom)}
        remap = np.asarray([lookup.get(d, -1) for d in vdom], np.int64)
        codes = np.where(codes >= 0, remap[np.maximum(codes, 0)], -1)
    return codes


class MojoScorer:
    """Numpy-only offline scorer — `EasyPredictModelWrapper` equivalent.

    predict() accepts a Frame or a numpy matrix in training-column order and
    returns the same columns the in-cluster scorer produces."""

    def __init__(self, meta: Dict, arrays: Dict[str, np.ndarray],
                 children: Optional[Dict[str, "MojoScorer"]] = None):
        self.meta = meta
        self.arrays = arrays
        self.children = children or {}
        self.algo = meta["algo"]
        self.model_id = meta.get("model_id", "artifact")
        self.x = meta["x"]
        self.y = meta["y"]
        self._native_forests: Dict[int, tuple] = {}  # k → converted arrays

    def scoring_signature(self) -> tuple:
        """Serving-cache key parts — mirrors H2OModel.scoring_signature so
        uploaded artifacts ride the same compiled-scorer cache."""
        x = self.x
        nf = len(x) if isinstance(x, (list, tuple)) else (1 if x else 0)
        return (nf, "float32")

    def _native_forest(self, k: int):
        """Contiguous ctypes-ready forest arrays, converted once per class
        (the serving hot path must not re-copy the model every call)."""
        if k not in self._native_forests:
            self._native_forests[k] = (
                np.ascontiguousarray(self.arrays[f"forest{k}_feat"], np.int32),
                np.ascontiguousarray(self.arrays[f"forest{k}_thr"], np.float32),
                np.ascontiguousarray(self.arrays[f"forest{k}_is_split"]).astype(np.uint8),
                np.ascontiguousarray(self.arrays[f"forest{k}_value"], np.float32),
            )
        return self._native_forests[k]

    # -- shared helpers -----------------------------------------------------
    def _matrix(self, data) -> np.ndarray:
        from .frame.frame import Frame

        if isinstance(data, Frame):
            from .models.shared_tree import frame_to_matrix

            X, _, _ = frame_to_matrix(
                data, self.x, expected_domains=self.meta.get("feature_domains")
            )
            return X
        return np.asarray(data, np.float64)

    @staticmethod
    def _score_one_forest(feat, thr, split, value, D: int,
                          X: np.ndarray) -> np.ndarray:
        """Summed leaf values of one stacked forest over raw feature rows —
        native C++ traversal (mojo_scorer.cpp) with a numpy fallback."""
        from .native import loader as native_loader

        total = native_loader.score_forest(feat, thr, split, value, D, X)
        if total is None:
            ntrees = feat.shape[0]
            total = np.zeros(X.shape[0])
            for t in range(ntrees):
                node = np.zeros(X.shape[0], np.int64)
                for _ in range(D):
                    f = feat[t][node]
                    s = split[t][node]
                    xv = X[np.arange(X.shape[0]), f]
                    right = np.isnan(xv) | (xv > thr[t][node])
                    child = 2 * node + 1 + (right & s).astype(np.int64)
                    node = np.where(s, child, node)
                total += value[t][node]
        return total

    def _tree_scores(self, X: np.ndarray) -> np.ndarray:
        meta = self.meta
        D = meta["max_depth"]
        outs = []
        for k in range(meta["n_forests"]):
            feat, thr, split, value = self._native_forest(k)
            total = self._score_one_forest(feat, thr, split, value, D, X)
            f0 = meta["f0"]
            f0k = f0[k] if isinstance(f0, list) else f0
            outs.append(total + (f0k if meta["mode"] != "drf" else 0.0))
        return np.column_stack(outs)

    def _expand_dinfo(self, data) -> np.ndarray:
        from .frame.frame import Frame

        di = self.meta["dinfo"]
        cols = []
        for kind, n, dom in di["spec"]:
            if isinstance(data, Frame):
                v = data.vec(n)
                raw = v.numeric_np()
                codes = np.asarray(v.data) if v.type == "enum" else None
                vdom = v.domain
            else:
                raise TypeError("dinfo models require a Frame input")
            if kind == "num":
                c = np.where(np.isnan(raw), di["col_means"].get(n, 0.0), raw)
                cols.append(c[:, None])
            else:
                codes = _remap_codes(codes, vdom, dom)
                K = len(dom)
                oh = np.zeros((len(codes), K))
                valid = codes >= 0
                oh[np.nonzero(valid)[0], codes[valid]] = 1.0
                if not di["use_all"] and K > 0:
                    oh = oh[:, 1:]
                cols.append(oh)
        X = np.concatenate(cols, axis=1)
        if di["standardize"] and "means" in self.arrays:
            X = (X - self.arrays["means"]) / self.arrays["stds"]
        return np.nan_to_num(X, nan=0.0)

    def predict_contributions(self, data):
        """Offline SHAP contributions + BiasTerm — the genmodel-side
        `predictContributions` (hex/genmodel/algos/tree/TreeSHAP.java via
        EasyPredictModelWrapper). Tree artifacts with recorded covers only;
        binomial/regression, as in-cluster."""
        from .frame.frame import Frame

        meta = self.meta
        if meta["kind"] != "tree":
            raise ValueError("predict_contributions requires a tree artifact")
        if meta["problem"] == "multinomial":
            raise ValueError("predict_contributions is not supported for "
                             "multinomial models")
        if "forest0_cover" not in self.arrays:
            raise ValueError("artifact has no node covers (exported before "
                             "TreeSHAP support); re-export the model")
        from .models.tree_shap import compute_contributions

        X = self._matrix(data)
        feat, thr, split, value = self._native_forest(0)
        cover = np.ascontiguousarray(self.arrays["forest0_cover"], np.float32)
        scale = 1.0 / max(meta["ntrees"], 1) if meta["mode"] == "drf" else 1.0
        f0 = meta["f0"]
        f0k = f0[0] if isinstance(f0, list) else f0
        contrib = compute_contributions(feat, thr, split, value, cover, X,
                                        scale, f0k)
        names = list(self.x) + ["BiasTerm"]
        return Frame.from_dict({n: contrib[:, j] for j, n in enumerate(names)})

    # -- prediction ---------------------------------------------------------
    def predict(self, data):
        from .frame.frame import Frame

        meta = self.meta
        kind = meta["kind"]
        if kind == "tree":
            X = self._matrix(data)
            m = self._tree_scores(X)
            problem = meta["problem"]
            if meta["mode"] == "drf":
                m = m / max(meta["ntrees"], 1)
                if problem == "binomial":
                    p1 = np.clip(m[:, 0], 0, 1)
                    probs = np.column_stack([1 - p1, p1])
                elif problem == "multinomial":
                    p = np.clip(m, 0, None)
                    probs = p / np.maximum(p.sum(axis=1, keepdims=True), 1e-12)
                else:
                    return Frame.from_dict({"predict": m[:, 0]})
            else:
                if problem == "binomial":
                    p1 = 1 / (1 + np.exp(-m[:, 0]))
                    probs = np.column_stack([1 - p1, p1])
                elif problem == "multinomial":
                    e = np.exp(m - m.max(axis=1, keepdims=True))
                    probs = e / e.sum(axis=1, keepdims=True)
                else:
                    out = m[:, 0]
                    if meta["distribution"] in ("poisson", "gamma", "tweedie"):
                        out = np.exp(out)
                    return Frame.from_dict({"predict": out})
            dom = meta["domain"]
            d = {"predict": np.asarray(dom, dtype=object)[probs.argmax(axis=1)]}
            for i, cls in enumerate(dom):
                d[str(cls)] = probs[:, i]
            return Frame.from_dict(d, column_types={"predict": "enum"})
        if kind == "glm":
            X = self._expand_dinfo(data)
            Xi = np.concatenate([X, np.ones((X.shape[0], 1))], axis=1)
            beta = self.arrays["beta"]
            eta = Xi @ beta.T
            fam = meta["family"]
            if fam in ("binomial", "quasibinomial", "fractionalbinomial"):
                p1 = 1 / (1 + np.exp(-np.clip(eta, -500, 500)))
                dom = meta["domain"]
                return Frame.from_dict({
                    "predict": np.asarray(dom, dtype=object)[(p1 > 0.5).astype(int)],
                    str(dom[0]): 1 - p1, str(dom[1]): p1,
                }, column_types={"predict": "enum"})
            if fam == "multinomial":
                e = np.exp(eta - eta.max(axis=1, keepdims=True))
                probs = e / e.sum(axis=1, keepdims=True)
                dom = meta["domain"]
                d = {"predict": np.asarray(dom, dtype=object)[probs.argmax(axis=1)]}
                for i, cls in enumerate(dom):
                    d[str(cls)] = probs[:, i]
                return Frame.from_dict(d, column_types={"predict": "enum"})
            if fam in ("poisson", "gamma", "tweedie"):
                eta = np.exp(eta)
            return Frame.from_dict({"predict": eta})
        if kind == "gam":
            from .ops.splines import spline_basis

            parts = []
            if meta["dinfo"]["spec"]:
                parts.append(self._expand_dinfo(data))
            for i, col in enumerate(meta["gam_cols"]):
                raw = np.nan_to_num(data.vec(col).numeric_np())
                B = (spline_basis(raw, self.arrays[f"gam{i}_knots"])
                     - self.arrays[f"gam{i}_center"])
                parts.append(B)
            X = np.concatenate(parts, axis=1)
            eta = (np.concatenate([X, np.ones((X.shape[0], 1))], axis=1)
                   @ self.arrays["beta"])
            fam = meta["family"]
            if fam == "binomial":
                p1 = 1 / (1 + np.exp(-np.clip(eta, -500, 500)))
                dom = meta["domain"]
                return Frame.from_dict({
                    "predict": np.asarray(dom, dtype=object)[
                        (p1 > 0.5).astype(int)],
                    str(dom[0]): 1 - p1, str(dom[1]): p1,
                }, column_types={"predict": "enum"})
            if fam in ("poisson", "gamma", "tweedie"):
                eta = np.exp(eta)
            return Frame.from_dict({"predict": eta})
        if kind == "uplift":
            X = self._matrix(data)
            feat = np.ascontiguousarray(self.arrays["uforest_feat"], np.int32)
            thr = np.ascontiguousarray(self.arrays["uforest_thr"], np.float32)
            split = np.ascontiguousarray(
                self.arrays["uforest_is_split"]).astype(np.uint8)
            value = np.ascontiguousarray(
                self.arrays["uforest_value"], np.float32)
            total = self._score_one_forest(feat, thr, split, value,
                                           meta["max_depth"], X)
            return Frame.from_dict(
                {"uplift_predict": total / max(meta["ntrees"], 1)})
        if kind == "isoforest":
            from .models.isolation_forest import anomaly_scores, forest_path_lengths

            X = self._matrix(data)
            trees = zip(self.arrays["if_feat"], self.arrays["if_thr"],
                        self.arrays["if_split"], self.arrays["if_leafn"])
            pl = forest_path_lengths(trees, X, self.meta["max_depth"])
            score = anomaly_scores(pl, self.meta["sample_size"])
            return Frame.from_dict({"predict": score, "mean_length": pl})
        if kind == "kmeans":
            X = self._expand_dinfo(data)
            c = self.arrays["centers_std"]
            d2 = (np.sum(X * X, axis=1, keepdims=True) - 2.0 * X @ c.T
                  + np.sum(c * c, axis=1)[None, :])
            return Frame.from_dict({"predict": d2.argmin(axis=1).astype(np.float64)})
        if kind == "pca":
            X = self._expand_dinfo(data)
            scores = X @ self.arrays["eigenvectors"]
            return Frame.from_dict(
                {f"PC{i+1}": scores[:, i] for i in range(self.meta["k"])})
        if kind == "deeplearning":
            X = self._expand_dinfo(data)
            h = X
            L = meta["n_layers"]
            act = meta["activation"].replace("WithDropout", "")
            for i in range(L):
                z = h @ self.arrays[f"W{i}"] + self.arrays[f"b{i}"]
                if i < L - 1:
                    if act == "Rectifier":
                        h = np.maximum(z, 0)
                    elif act == "Tanh":
                        h = np.tanh(z)
                    else:  # Maxout
                        h = z.reshape(z.shape[0], -1, 2).max(axis=2)
                else:
                    h = z
            problem = meta["problem"]
            if problem in ("binomial", "multinomial"):
                e = np.exp(h - h.max(axis=1, keepdims=True))
                probs = e / e.sum(axis=1, keepdims=True)
                dom = meta["domain"]
                d = {"predict": np.asarray(dom, dtype=object)[probs.argmax(axis=1)]}
                for i, cls in enumerate(dom):
                    d[str(cls)] = probs[:, i]
                return Frame.from_dict(d, column_types={"predict": "enum"})
            out = h[:, 0]
            if meta["distribution"] in ("poisson", "gamma", "tweedie"):
                out = np.exp(out)
            return Frame.from_dict({"predict": out})
        if kind == "eif":
            X = self._expand_dinfo(data)
            depth = meta["depth"]
            dirs = self.arrays["eif_dirs"]
            thrs = self.arrays["eif_thrs"]
            splits = self.arrays["eif_splits"]
            counts = self.arrays["eif_counts"]
            N = X.shape[0]
            pls = []
            for t in range(dirs.shape[0]):
                idx = np.zeros(N, np.int64)
                depth_stop = np.full(N, float(depth))
                stop_node = np.zeros(N, np.int64)
                live = np.ones(N, bool)
                for d in range(depth):
                    node = 2 ** d - 1 + idx
                    s = splits[t][node]
                    proj = np.sum(X * dirs[t][node], axis=1)
                    stopping = live & ~s
                    depth_stop[stopping] = d
                    stop_node[stopping] = node[stopping]
                    live &= s
                    go_right = live & (proj > thrs[t][node])
                    idx = np.where(live, 2 * idx + go_right.astype(np.int64),
                                   idx)
                stop_node = np.where(live, 2 ** depth - 1 + idx, stop_node)
                nleaf = counts[t][stop_node]
                credit = np.where(
                    nleaf > 1.5,
                    2.0 * (np.log(np.maximum(nleaf - 1.0, 1.0)) + 0.5772156649)
                    - 2.0 * (nleaf - 1.0) / np.maximum(nleaf, 1.0),
                    0.0)
                pls.append(depth_stop + credit)
            mean_length = np.mean(pls, axis=0)
            S = max(meta["sample_size"], 2.0)
            cS = (2.0 * (np.log(S - 1.0) + 0.5772156649)
                  - 2.0 * (S - 1.0) / S)
            score = 2.0 ** (-mean_length / cS)
            return Frame.from_dict({"anomaly_score": score,
                                    "mean_length": mean_length})
        if kind == "stackedensemble":
            lvl1 = {}
            problem = meta["problem"]
            for i in range(meta["n_base"]):
                base = self.children[f"base{i}"]
                pf = base.predict(data)
                bdom = base.meta.get("domain")
                if problem == "multinomial":
                    for k2, cls in enumerate(bdom):
                        lvl1[f"m{i}_p{k2}"] = pf.vec(str(cls)).numeric_np()
                elif problem == "binomial":
                    lvl1[f"m{i}"] = pf.vec(str(bdom[1])).numeric_np()
                else:
                    lvl1[f"m{i}"] = pf.vec("predict").numeric_np()
            return self.children["meta"].predict(Frame.from_dict(lvl1))
        if kind == "word2vec":
            return self.transform(data)
        if kind == "glrm":
            X = self._glrm_project(data)
            R = X @ self.arrays["glrm_y"]
            names = meta["dinfo"]["coef_names"]
            return Frame.from_dict(
                {f"reconstr_{names[j]}": R[:, j] for j in range(R.shape[1])})
        if kind == "targetencoder":
            out = {n: v for n, v in zip(data.names, data.vecs())}
            for i, ent in enumerate(meta["te_cols"]):
                col, dom = ent["col"], ent["domain"]
                if col not in data.names:
                    continue
                v = data.vec(col)
                codes = (np.asarray(v.data) if v.type == "enum"
                         else v.numeric_np().astype(np.int64))
                if v.type == "enum":
                    codes = _remap_codes(codes, v.domain, dom)
                sums = self.arrays[f"te{i}_sums"]
                cnts = self.arrays[f"te{i}_cnts"]
                prior = meta["prior"]
                enc = np.full(len(codes), prior)
                ok = (codes >= 0) & (codes < len(sums))
                ci = np.maximum(codes, 0)
                s, c = sums[ci], cnts[ci]
                if meta["blending"]:
                    # exactly TargetEncoderModel._blend: mean is s/max(c,ε)
                    # (0.0 for empty levels — NOT the prior)
                    with np.errstate(over="ignore"):
                        lam = 1.0 / (1.0 + np.exp(
                            -(c - meta["te_k"]) / max(meta["te_f"], 1e-12)))
                    e = lam * (s / np.maximum(c, 1e-12)) + (1 - lam) * prior
                else:
                    e = np.where(c > 0, s / np.maximum(c, 1e-12), prior)
                enc[ok] = e[ok]
                from .frame.vec import Vec

                out[f"{col}_te"] = Vec(enc.astype(np.float32), "real")
            return Frame(out)
        if kind == "rulefit":
            cols = [data.vec(n).numeric_np() for n in self.x]
            X = (np.column_stack(cols) if cols
                 else np.zeros((data.nrow, 0)))
            col_of = {n: i for i, n in enumerate(self.x)}
            d = {}
            for i, conds in enumerate(meta["rules"]):
                m = np.ones(X.shape[0], bool)
                for fname, thr, right in conds:
                    col = X[:, col_of[fname]]
                    if right:
                        m &= np.isnan(col) | (col > thr)
                    else:
                        m &= ~np.isnan(col) & (col <= thr)
                d[f"rule_{i}"] = m.astype(np.float64)
            for c in meta["lin_cols"]:
                lo, hi, sd = meta["lin_stats"][c]
                col = np.clip(np.nan_to_num(data.vec(c).numeric_np()), lo, hi)
                d[f"linear.{c}"] = 0.4 * col / max(sd, 1e-12)
            return self.children["glm"].predict(Frame.from_dict(d))
        if kind == "coxph":
            X = self._expand_dinfo(data)
            return Frame.from_dict({"lp": X @ self.arrays["beta"]})
        if kind == "naivebayes":
            n = data.nrow
            priors = self.arrays["nb_priors"]
            K = len(priors)
            logp = np.tile(np.log(priors)[None, :], (n, 1))
            for ent in meta["nb_spec"]:
                name = ent["name"]
                v = data.vec(name)
                if ent["kind"] == "num":
                    col = v.numeric_np()
                    st = self.arrays[f"nb_num_{name}"]
                    mean, sd = st[:, 0], st[:, 1]
                    valid = ~np.isnan(col)
                    ll = (-0.5 * np.log(2 * np.pi * sd[None, :] ** 2)
                          - 0.5 * ((np.where(valid, col, 0.0)[:, None]
                                    - mean[None, :]) / sd[None, :]) ** 2)
                    logp += np.where(valid[:, None], ll, 0.0)
                else:
                    probs = self.arrays[f"nb_cat_{name}"]
                    dom = ent["domain"]
                    codes = _remap_codes(np.asarray(v.data), v.domain, dom)
                    valid = codes >= 0
                    ll = np.log(probs[:, np.maximum(codes, 0)]).T
                    logp += np.where(valid[:, None], ll, 0.0)
            mshift = logp - logp.max(axis=1, keepdims=True)
            probs2 = np.exp(mshift) / np.exp(mshift).sum(axis=1,
                                                         keepdims=True)
            dom = meta["domain"]
            lab = probs2.argmax(axis=1)
            d = {"predict": np.asarray(dom, dtype=object)[lab]}
            for i, cls in enumerate(dom):
                d[str(cls)] = probs2[:, i]
            return Frame.from_dict(d, column_types={"predict": "enum"})
        if kind == "isotonic":
            xname = self.x if isinstance(self.x, str) else self.x[0]
            col = data.vec(xname).numeric_np()
            tx, ty = self.arrays["iso_tx"], self.arrays["iso_ty"]
            p = np.interp(col, tx, ty)
            if meta["out_of_bounds"].lower() == "na":
                p = np.where((col < tx[0]) | (col > tx[-1]), np.nan, p)
            p = np.where(np.isnan(col), np.nan, p)
            return Frame.from_dict({"predict": p})
        if kind == "svd":
            X = self._expand_dinfo(data)
            scores = (X @ self.arrays["svd_v"]
                      ) / np.maximum(self.arrays["svd_d"][None, :], 1e-300)
            return Frame.from_dict(
                {f"u{i+1}": scores[:, i] for i in range(scores.shape[1])})
        raise ValueError(f"unknown artifact kind {kind!r}")

    # -- non-predict scoring surfaces ---------------------------------------
    def _glrm_project(self, data) -> np.ndarray:
        """GLRM row loadings for new data — `_expand` keeps NaNs so the
        observation mask survives (GLRMModel._project semantics)."""
        from .frame.frame import Frame

        di = self.meta["dinfo"]
        cols = []
        for knd, n, dom in di["spec"]:
            v = data.vec(n)
            if knd == "num":
                cols.append(v.numeric_np()[:, None])
            else:
                codes = _remap_codes(np.asarray(v.data), v.domain, dom)
                K = len(dom)
                oh = np.zeros((len(codes), K))
                valid = codes >= 0
                oh[np.nonzero(valid)[0], codes[valid]] = 1.0
                if not di["use_all"] and K > 0:
                    oh = oh[:, 1:]
                cols.append(oh)
        A = np.concatenate(cols, axis=1)
        if "means" in self.arrays:
            A = (A - self.arrays["means"]) / self.arrays["stds"]
        Y = self.arrays["glrm_y"]
        k = Y.shape[0]
        mask = ~np.isnan(A)
        A0 = np.nan_to_num(A, nan=0.0)
        lam = 1e-6
        Xn = np.zeros((A.shape[0], k))
        YT = Y.T
        for i in range(A.shape[0]):
            m = mask[i]
            G = YT[m].T @ YT[m] + lam * np.eye(k)
            Xn[i] = np.linalg.solve(G, YT[m].T @ A0[i, m])
        return Xn

    def transform(self, data, aggregate_method: str = "NONE"):
        """word2vec words→vectors / glrm archetype loadings / targetencoder
        column appends — the model-side `transform` surfaces, offline."""
        from .frame.frame import Frame

        kind = self.meta["kind"]
        if kind == "glrm":
            Xn = self._glrm_project(data)
            return Frame.from_dict(
                {f"Arch{j+1}": Xn[:, j] for j in range(Xn.shape[1])})
        if kind == "targetencoder":
            return self.predict(data)
        if kind != "word2vec":
            raise ValueError(f"transform is not defined for kind {kind!r}")
        vecs, vocab, index = self._w2v()
        col = data.vecs()[0]
        words = (col.to_numpy() if col.type == "string" else np.asarray(
            [col.domain[c] if c >= 0 else None
             for c in np.asarray(col.data)], dtype=object))
        dim = vecs.shape[1]
        if aggregate_method.upper() == "NONE":
            out = np.full((len(words), dim), np.nan)
            for i, w in enumerate(words):
                if w is not None and w in index:
                    out[i] = vecs[index[w]]
            return Frame.from_dict(
                {f"C{j+1}": out[:, j] for j in range(dim)})
        sents, cur = [], []
        for w in words:
            if w is None:
                sents.append(cur)
                cur = []
            else:
                cur.append(w)
        sents.append(cur)
        rows = []
        for s in sents:
            hit = [vecs[index[w]] for w in s if w in index]
            rows.append(np.mean(hit, axis=0) if hit
                        else np.full(dim, np.nan))
        out = np.stack(rows)
        return Frame.from_dict({f"C{j+1}": out[:, j] for j in range(dim)})

    def _w2v(self):
        """(vectors, vocab, word→index) decoded once per scorer — the
        convert-once convention of `_native_forest`."""
        if "_w2v_cache" not in self.__dict__:
            vocab = [str(w) for w in self.arrays["w2v_vocab"]]
            self._w2v_cache = (self.arrays["w2v_vectors"], vocab,
                               {w: i for i, w in enumerate(vocab)})
        return self._w2v_cache

    def find_synonyms(self, word: str, count: int = 20) -> Dict[str, float]:
        if self.meta["kind"] != "word2vec":
            raise ValueError("find_synonyms requires a word2vec artifact")
        vecs, vocab, index = self._w2v()
        if word not in index:
            return {}
        v = vecs[index[word]]
        norms = (np.linalg.norm(vecs, axis=1)
                 * max(np.linalg.norm(v), 1e-12))
        sims = vecs @ v / np.maximum(norms, 1e-12)
        out = {}
        for i in np.argsort(-sims):
            if vocab[i] == word:
                continue
            out[vocab[i]] = float(sims[i])
            if len(out) >= count:
                break
        return out
