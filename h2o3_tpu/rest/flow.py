"""Flow — the built-in web UI, served at `/flow/`.

Reference parity: `h2o-web/` (H2O Flow, the CoffeeScript notebook UI served
by the JVM at `/flow/index.html`). A deliberately small single-page analog
covering Flow's operational core — cloud status, frames (with column
summaries), models (metrics, variable importances), jobs, grids, AutoML
leaderboards, a Rapids cell — plus the NOTEBOOK: an editable list of
Rapids/plot cells with per-cell outputs, runnable top to bottom, and
save/load of named flows through `/99/Flows` (the reference persists
`.flow` documents the same way). Plot cells render a column histogram as
inline SVG from `(hist (cols <frame> [i]) 20)`.
"""

FLOW_HTML = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>h2o3-tpu Flow</title>
<style>
  :root { --fg:#222; --muted:#777; --line:#e0e0e0; --accent:#1565c0; }
  body { font-family: -apple-system, 'Segoe UI', Roboto, sans-serif;
         margin:0; color:var(--fg); }
  header { padding:10px 20px; border-bottom:1px solid var(--line);
           display:flex; align-items:baseline; gap:16px; }
  header h1 { font-size:18px; margin:0; }
  header span { color:var(--muted); font-size:13px; }
  nav { display:flex; gap:4px; padding:8px 20px; border-bottom:1px solid var(--line); }
  nav button { border:1px solid var(--line); background:#fff; padding:6px 14px;
               border-radius:4px; cursor:pointer; font-size:13px; }
  nav button.active { background:var(--accent); color:#fff; border-color:var(--accent); }
  main { padding:16px 20px; }
  table { border-collapse:collapse; font-size:13px; margin:8px 0; }
  th, td { border:1px solid var(--line); padding:4px 10px; text-align:left; }
  th { background:#f7f7f7; }
  .muted { color:var(--muted); }
  textarea { width:100%; font-family:monospace; font-size:13px; }
  pre { background:#f7f7f7; padding:10px; overflow:auto; font-size:12px; }
  .err { color:#b00020; }
</style>
</head>
<body>
<header><h1>H2O Flow</h1><span id="cloud" class="muted">connecting…</span></header>
<nav id="tabs"></nav>
<main id="view">loading…</main>
<script>
const TABS = ["Frames", "Models", "Jobs", "Grids", "AutoML", "Rapids",
              "Notebook"];
let active = "Frames";
let cells = [{type: "rapids", src: "(nrow frame)", out: ""}];
const esc = (v) => String(v).replace(/[&<>"']/g,
  (c) => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));
async function api(path, opts) {
  const r = await fetch(path, opts);
  const j = await r.json();
  if (!r.ok) throw new Error(j.msg || r.statusText);
  return j;
}
function table(rows, cols) {
  if (!rows.length) return "<p class='muted'>none</p>";
  cols = cols || Object.keys(rows[0]);
  const h = cols.map(c => `<th>${esc(c)}</th>`).join("");
  const b = rows.map(r => "<tr>" + cols.map(c => {
    let v = r[c];
    if (v && typeof v === "object" && "name" in v) v = v.name;
    if (typeof v === "number") v = +v.toFixed(5);
    return `<td>${v === null || v === undefined ? "" : esc(v)}</td>`;
  }).join("") + "</tr>").join("");
  return `<table><tr>${h}</tr>${b}</table>`;
}
const views = {
  async Frames() {
    const fr = (await api("/3/Frames")).frames || [];
    let html = "<h3>Frames</h3>" + table(fr.map(f => ({
      key: f.frame_id, rows: f.rows, columns: f.columns })));
    html += "<p class='muted'>click a key in the table? use the summary box:</p>";
    html += "<input id='fkey' placeholder='frame key'> <button onclick='frameSummary()'>summary</button><div id='fsum'></div>";
    return html;
  },
  async Models() {
    const ms = (await api("/3/Models")).models || [];
    return "<h3>Models</h3>" + table(ms.map(m => {
      const tm = (m.output || {}).training_metrics || {};
      return { model_id: m.model_id, algo: m.algo,
               auc: tm.auc, rmse: tm.rmse, logloss: tm.logloss };
    }));
  },
  async Jobs() {
    const js = (await api("/3/Jobs")).jobs || [];
    return "<h3>Jobs</h3>" + table(js.map(j => ({
      key: j.key, status: j.status, progress: j.progress, dest: j.dest })));
  },
  async Grids() {
    const gs = (await api("/99/Grids")).grids || [];
    return "<h3>Grids</h3>" + table(gs.map(g => ({
      grid_id: g.grid_id, models: (g.model_ids || []).length,
      hyper: (g.hyper_names || []).join(", ") })));
  },
  async AutoML() {
    return "<h3>AutoML</h3><input id='proj' placeholder='project name'>" +
      " <button onclick='loadLb()'>leaderboard</button><div id='lb'></div>";
  },
  async Rapids() {
    return "<h3>Rapids</h3><textarea id='ast' rows='3'>(nrow frame)</textarea>" +
      "<br><button onclick='runRapids()'>run</button><pre id='rout'></pre>";
  },
  async Notebook() {
    let html = "<h3>Notebook</h3><p class='muted'>cells run top to bottom; " +
      "plot cells take <code>&lt;frame-key&gt; &lt;column-index&gt;</code>" +
      "</p><div>" +
      "<input id='flowname' placeholder='flow name'> " +
      "<button onclick='saveFlow()'>save</button> " +
      "<button onclick='loadFlow()'>load</button> " +
      "<button onclick='listFlows()'>list</button> " +
      "<button onclick='runAll()'>run all</button> " +
      "<span id='flowmsg' class='muted'></span></div><div id='cells'></div>" +
      "<button onclick='addCell(\\"rapids\\")'>+ rapids cell</button> " +
      "<button onclick='addCell(\\"plot\\")'>+ plot cell</button>";
    setTimeout(renderCells, 0);
    return html;
  },
};
function renderCells() {
  const el = document.getElementById("cells");
  if (!el) return;
  el.innerHTML = cells.map((c, i) =>
    `<div style="border:1px solid var(--line);border-radius:4px;` +
    `padding:8px;margin:8px 0">` +
    `<span class='muted'>[${i}] ${c.type}</span> ` +
    `<button onclick='runCell(${i})'>run</button> ` +
    `<button onclick='delCell(${i})'>delete</button>` +
    `<textarea rows='2' oninput='cells[${i}].src=this.value'>` +
    `${esc(c.src)}</textarea><div id='cellout${i}'>${c.out || ""}</div>` +
    `</div>`).join("");
}
function addCell(type) {
  cells.push({type, src: type === "plot" ? "frame 0" : "(nrow frame)",
              out: ""});
  renderCells();
}
function delCell(i) { cells.splice(i, 1); renderCells(); }
function svgHist(counts, edges) {
  const W = 420, H = 120, n = counts.length;
  const mx = Math.max(...counts, 1);
  const bars = counts.map((c, i) => {
    const h = Math.round((c / mx) * (H - 10));
    const x = Math.round(i * (W / n));
    return `<rect x="${x}" y="${H - h}" width="${Math.max(W / n - 1, 1)}"` +
      ` height="${h}" fill="#1565c0"></rect>`;
  }).join("");
  return `<svg width="${W}" height="${H}">${bars}</svg>`;
}
async function runCell(i) {
  const c = cells[i];
  const out = document.getElementById("cellout" + i);
  try {
    if (c.type === "plot") {
      const parts = c.src.trim().split(/\\s+/);
      const ast = `(hist (cols ${parts[0]} [${parts[1] || 0}]) 20)`;
      const r = await api("/99/Rapids", { method: "POST",
        headers: {"Content-Type": "application/json"},
        body: JSON.stringify({ ast, rows: 64 }) });
      const cols = r.columns ||
        (r.frames && r.frames[0] && r.frames[0].columns) || [];
      const counts = (cols.find(x => /count/i.test(x.label)) || cols[1]
                      || {data: []}).data || [];
      c.out = svgHist(counts.map(Number), []);
    } else {
      const r = await api("/99/Rapids", { method: "POST",
        headers: {"Content-Type": "application/json"},
        body: JSON.stringify({ ast: c.src }) });
      c.out = "<pre>" + esc(JSON.stringify(r, null, 2).slice(0, 4000)) +
              "</pre>";
    }
  } catch (e) { c.out = `<p class='err'>${esc(e.message)}</p>`; }
  if (out) out.innerHTML = c.out;
}
async function runAll() {
  for (let i = 0; i < cells.length; i++) await runCell(i);
}
async function saveFlow() {
  const name = document.getElementById("flowname").value;
  const msg = document.getElementById("flowmsg");
  try {
    await api("/99/Flows", { method: "POST",
      headers: {"Content-Type": "application/json"},
      body: JSON.stringify({ name,
        cells: cells.map(c => ({type: c.type, src: c.src})) }) });
    msg.textContent = "saved " + name;
  } catch (e) { msg.textContent = "save failed: " + e.message; }
}
async function loadFlow() {
  const name = document.getElementById("flowname").value;
  const msg = document.getElementById("flowmsg");
  try {
    const r = await api("/99/Flows/" + encodeURIComponent(name));
    cells = (r.cells || []).map(c => ({...c, out: ""}));
    renderCells();
    msg.textContent = "loaded " + name;
  } catch (e) { msg.textContent = "load failed: " + e.message; }
}
async function listFlows() {
  const msg = document.getElementById("flowmsg");
  try {
    const r = await api("/99/Flows");
    msg.textContent = "flows: " +
      (r.flows.map(f => f.name).join(", ") || "(none)");
  } catch (e) { msg.textContent = e.message; }
}
async function frameSummary() {
  const k = document.getElementById("fkey").value;
  try {
    const s = (await api(`/3/Frames/${encodeURIComponent(k)}/summary`)).frames[0];
    document.getElementById("fsum").innerHTML = table(s.columns.map(c => ({
      column: c.label, type: c.type, mean: c.mean, min: c.min, max: c.max,
      missing: c.nacnt })));
  } catch (e) { document.getElementById("fsum").innerHTML = `<p class='err'>${esc(e.message)}</p>`; }
}
async function loadLb() {
  const p = document.getElementById("proj").value;
  try {
    const lb = (await api(`/99/Leaderboards/${encodeURIComponent(p)}`)).leaderboard.rows;
    document.getElementById("lb").innerHTML = table(lb);
  } catch (e) { document.getElementById("lb").innerHTML = `<p class='err'>${esc(e.message)}</p>`; }
}
async function runRapids() {
  const ast = document.getElementById("ast").value;
  const out = document.getElementById("rout");
  try {
    const r = await api("/99/Rapids", { method: "POST",
      headers: {"Content-Type": "application/json"},
      body: JSON.stringify({ ast }) });
    out.textContent = JSON.stringify(r, null, 2);
  } catch (e) { out.textContent = "error: " + e.message; }
}
function renderTabs() {
  document.getElementById("tabs").innerHTML = TABS.map(t =>
    `<button class="${t === active ? 'active' : ''}" onclick="go('${t}')">${t}</button>`).join("");
}
async function go(tab) {
  active = tab; renderTabs();
  const v = document.getElementById("view");
  try { v.innerHTML = await views[tab](); }
  catch (e) { v.innerHTML = `<p class='err'>${esc(e.message)}</p>`; }
}
(async () => {
  try {
    const c = await api("/3/Cloud");
    document.getElementById("cloud").textContent =
      `${c.cloud_name} · ${c.cloud_size} node(s) · v${c.version}`;
  } catch (e) { document.getElementById("cloud").textContent = "cloud unreachable"; }
  renderTabs(); go(active);
  setInterval(() => { if (active === "Jobs") go("Jobs"); }, 3000);
})();
</script>
</body>
</html>
"""
