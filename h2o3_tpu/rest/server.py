"""RequestServer — the versioned JSON-over-HTTP route table.

Reference parity: `h2o-core/src/main/java/water/api/RequestServer.java`
(route registration, versioned paths), `ModelBuilderHandler.java` (train via
`POST /3/ModelBuilders/{algo}`), `FramesHandler`/`ModelsHandler`/
`JobsHandler`/`PredictionsHandler`/`LogsHandler`/`ProfilerHandler`, plus
`/99/Rapids` (`water/rapids/Rapids.java`). Jetty is replaced by the stdlib
ThreadingHTTPServer — the webserver-iface indirection exists so the server
can be swapped, same as `h2o-webserver-iface/`.

Training runs on a worker thread under a `Job` so `/3/Jobs/{id}` polling
behaves like the reference's async job keys.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

import numpy as np

from ..frame.frame import Frame
from ..frame.rapids_expr import RapidsSession
from ..models.model_base import H2OModel, Job
from ..runtime import metrics_registry as registry
from ..runtime import tracing
from ..runtime.dkv import DKV
from ..runtime.log import Log
from ..runtime.timeline import Timeline
from . import schemas

# per-route request accounting in the central registry: counter + latency
# histogram labeled by handler name (bounded cardinality — the route table
# is fixed), so the REST face itself is scrapable at GET /3/Metrics
_REQ_COUNT = registry.counter("h2o3_rest_requests",
                              "REST requests dispatched, per handler",
                              labelnames=("handler", "status"))
_REQ_MS = registry.histogram("h2o3_rest_request_ms",
                             "REST request wall time (ms), per handler",
                             labelnames=("handler",))


def _json_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        v = float(o)
        return v if np.isfinite(v) else None
    if isinstance(o, np.ndarray):
        return o.tolist()
    return str(o)


def _sanitize(o):
    """Replace non-finite floats with null BEFORE dumps — json.dumps never
    calls `default` for native floats, so NaN would otherwise serialize as a
    bare (invalid-JSON) NaN token."""
    if isinstance(o, float):
        return o if np.isfinite(o) else None
    if isinstance(o, dict):
        return {k: _sanitize(v) for k, v in o.items()}
    if isinstance(o, (list, tuple)):
        return [_sanitize(v) for v in o]
    return o


def _frame_summary(fr: Frame, rows: int = 10) -> Dict:
    cols = []
    for n in fr.names:
        v = fr.vec(n)
        c = dict(label=n, type=v.type)
        if v.type in ("real", "int", "time"):
            c.update(mean=v.mean(), min=v.min(), max=v.max(), nacnt=v.nacnt())
        elif v.type == "enum":
            c.update(domain=v.domain, nacnt=v.nacnt())
        head = v.to_numpy()[:rows]
        c["data"] = [None if (isinstance(x, float) and np.isnan(x)) else x
                     for x in head.tolist()]
        cols.append(c)
    return dict(frame_id=dict(name=fr.key), rows=fr.nrow,
                num_columns=fr.ncol, columns=cols)


def _model_json(m: H2OModel) -> Dict:
    out = dict(
        model_id=dict(name=m.model_id),
        algo=m.algo,
        parameters=[dict(name=k, actual_value=v)
                    for k, v in m.parms.actual_params.items()
                    if not k.startswith("_")],
        output=dict(
            training_metrics=m.training_metrics._ser() if m.training_metrics else None,
            validation_metrics=m.validation_metrics._ser() if m.validation_metrics else None,
            cross_validation_metrics=(m.cross_validation_metrics._ser()
                                      if m.cross_validation_metrics else None),
            scoring_history=m.scoring_history,
            variable_importances=m.varimp_table,
            run_time=m.run_time,
        ),
    )
    return out


class _PayloadTooLarge(Exception):
    def __init__(self, n):
        super().__init__(f"request body of {n} bytes exceeds the "
                         "H2O3_MAX_BODY_MB cap")


class _Handler(BaseHTTPRequestHandler):
    server_version = "h2o3tpu"
    protocol_version = "HTTP/1.1"
    timeout = 120          # bounds slow-loris reads AND deferred TLS handshakes

    # route table (method, regex) → handler name — RequestServer.register
    ROUTES = [
        ("GET", r"^/(?:flow(?:/index\.html)?/?)?$", "flow"),
        ("GET", r"^/3/Cloud/?$", "cloud"),
        ("GET", r"^/3/About$", "about"),
        ("POST", r"^/3/ImportFiles$", "import_files"),
        ("POST", r"^/3/ParseSetup$", "parse_setup"),
        ("POST", r"^/3/Parse$", "parse"),
        ("GET", r"^/3/Frames$", "frames_list"),
        ("GET", r"^/3/Frames/([^/]+)/summary$", "frame_summary"),
        ("GET", r"^/3/Frames/([^/]+)$", "frame_get"),
        ("DELETE", r"^/3/Frames/([^/]+)$", "frame_delete"),
        ("POST", r"^/3/ModelBuilders/([^/]+)$", "train"),
        ("GET", r"^/3/ModelBuilders/([^/]+)$", "builder_schema"),
        ("GET", r"^/3/Models$", "models_list"),
        ("GET", r"^/3/Models/([^/]+)$", "model_get"),
        ("DELETE", r"^/3/Models/([^/]+)$", "model_delete"),
        ("POST", r"^/3/Predictions/models/([^/]+)/frames/([^/]+)$", "predict"),
        ("GET", r"^/3/Serving/metrics$", "serving_metrics"),
        ("GET", r"^/3/Faults$", "faults_get"),
        ("POST", r"^/3/Faults$", "faults_set"),
        ("DELETE", r"^/3/Faults$", "faults_delete"),
        ("GET", r"^/3/Ingest/metrics$", "ingest_metrics"),
        ("GET", r"^/3/Munge/metrics$", "munge_metrics"),
        ("GET", r"^/3/Training/metrics$", "training_metrics"),
        ("DELETE", r"^/3/Serving/cache$", "serving_cache_clear"),
        ("POST", r"^/3/ModelMetrics/models/([^/]+)/frames/([^/]+)$", "model_metrics"),
        ("GET", r"^/3/Jobs$", "jobs_list"),
        ("GET", r"^/3/Jobs/([^/]+)$", "job_get"),
        ("POST", r"^/99/Rapids$", "rapids"),
        ("GET", r"^/3/Logs(?:/download)?$", "logs"),
        ("GET", r"^/3/Timeline$", "timeline"),
        ("GET", r"^/3/Metrics$", "metrics"),
        ("GET", r"^/3/Memory$", "memory"),
        ("GET", r"^/3/Trace$", "trace"),
        ("GET", r"^/3/Supervisor$", "supervisor_get"),
        ("GET", r"^/3/Fleet$", "fleet_get"),
        ("POST", r"^/3/Fleet$", "fleet_set"),
        ("DELETE", r"^/3/Fleet$", "fleet_delete"),
        ("GET", r"^/3/Profiler$", "profiler"),
        ("GET", r"^/3/Metadata/schemas$", "metadata_schemas"),
        ("POST", r"^/3/Frames/([^/]+)/export$", "frame_export"),
        ("POST", r"^/99/Models\.bin/([^/]+)$", "model_save"),
        ("POST", r"^/99/Models\.bin$", "model_load"),
        ("POST", r"^/3/PostFile$", "post_file"),
        ("POST", r"^/99/Grid/([^/]+)$", "grid_train"),
        ("GET", r"^/99/Grids$", "grids_list"),
        ("GET", r"^/99/Grids/([^/]+)$", "grid_get"),
        ("POST", r"^/99/AutoMLBuilder$", "automl_build"),
        ("GET", r"^/99/AutoML/([^/]+)$", "automl_get"),
        ("GET", r"^/99/Leaderboards/([^/]+)$", "leaderboard_get"),
        ("POST", r"^/3/Recovery$", "recovery"),
        ("POST", r"^/3/Shutdown$", "shutdown"),
        ("GET", r"^/99/Flows$", "flows_list"),
        ("POST", r"^/99/Flows$", "flow_save"),
        ("GET", r"^/99/Flows/([^/]+)$", "flow_load"),
        ("DELETE", r"^/99/Flows/([^/]+)$", "flow_delete"),
        ("GET", r"^/3/Tree$", "tree"),
        ("GET", r"^/3/ModelMetrics$", "model_metrics_list"),
        ("GET", r"^/99/Typeahead/files$", "typeahead"),
        ("GET", r"^/3/WaterMeterCpuTicks/(\d+)$", "water_meter"),
        ("GET", r"^/3/NetworkTest$", "network_test"),
        ("POST", r"^/3/GarbageCollect$", "garbage_collect"),
        ("POST", r"^/3/ModelBuilders/([^/]+)/parameters$", "validate_params"),
        ("GET", r"^/3/Models/([^/]+)/mojo$", "model_mojo"),
        ("GET", r"^/3/DownloadDataset(?:\.bin)?$", "download_dataset"),
        ("POST", r"^/3/SplitFrame$", "split_frame"),
        ("POST", r"^/4/sessions$", "session_open"),
        ("DELETE", r"^/4/sessions/([^/]+)$", "session_close"),
        ("DELETE", r"^/3/DKV$", "remove_all"),
        ("DELETE", r"^/3/DKV/([^/]+)$", "remove_key"),
        ("POST", r"^/3/LogAndEcho$", "log_and_echo"),
        ("GET", r"^/3/Capabilities$", "capabilities"),
        ("GET", r"^/3/Ping$", "ping"),
        ("GET", r"^/3/Frames/([^/]+)/columns/([^/]+)/summary$",
         "column_summary"),
        ("POST", r"^/3/CreateFrame$", "create_frame"),
        ("POST", r"^/3/Interaction$", "interaction"),
        ("POST", r"^/3/MissingInserter$", "missing_inserter"),
        ("GET", r"^/3/ModelBuilders$", "builders_list"),
        ("POST", r"^/3/Jobs/([^/]+)/cancel$", "job_cancel"),
        ("GET", r"^/3/Frames/([^/]+)/columns$", "frame_columns"),
        ("GET", r"^/3/Frames/([^/]+)/columns/([^/]+)/domain$",
         "column_domain"),
        ("POST", r"^/3/Tabulate$", "tabulate"),
        ("GET", r"^/3/JStack$", "jstack"),
        ("POST", r"^/3/PartialDependence$", "pdp"),
        ("GET", r"^/3/PartialDependence/([^/]+)$", "pdp_get"),
        ("GET", r"^/3/Word2VecSynonyms$", "w2v_synonyms"),
        ("POST", r"^/3/Word2VecTransform$", "w2v_transform"),
        ("GET", r"^/3/Metadata/endpoints$", "metadata_endpoints"),
        ("POST", r"^/3/UnlockKeys$", "unlock_keys"),
        ("GET", r"^/3/Router$", "router_get"),
        ("POST", r"^/3/Router$", "router_post"),
        ("POST", r"^/3/Router/models/([^/]+)/frames/([^/]+)$",
         "router_predict"),
        ("POST", r"^/3/Serving/warm$", "serving_warm"),
    ]

    def log_message(self, fmt, *args):  # route access logs into our Log
        Log.debug("REST " + fmt % args)

    # -- plumbing ------------------------------------------------------------
    def _send(self, obj, status: int = 200,
              headers: Optional[Dict[str, str]] = None):
        body = json.dumps(_sanitize(obj), default=_json_default).encode()
        self._send_raw(body, "application/json", status=status,
                       headers=headers)

    def _send_raw(self, body: bytes, content_type: str, status: int = 200,
                  headers: Optional[Dict[str, str]] = None):
        self._status = status
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        tid = getattr(self, "_trace_id", None)
        if tid:
            # echo the request's trace id (minted server-side when the
            # client sent none) so callers can fetch GET /3/Trace?trace_id=
            self.send_header("X-H2O3-Trace-Id", tid)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _body_cap(self) -> int:
        """Request-size cap (413 beyond it): a hand-rolled HTTP face must
        not buffer unbounded bodies (Jetty's maxFormContentSize stance)."""
        return int(os.environ.get("H2O3_MAX_BODY_MB", 512)) << 20

    def _read_body(self) -> bytes:
        ln = int(self.headers.get("Content-Length") or 0)
        cap = self._body_cap()
        if ln > cap:
            # drain (bounded) so the client can read the 413 instead of a
            # broken pipe, then refuse; past 4x the cap, hard-close
            left = min(ln, 4 * cap)
            while left > 0:
                chunk = self.rfile.read(min(left, 1 << 20))
                if not chunk:
                    break
                left -= len(chunk)
            self.close_connection = True
            raise _PayloadTooLarge(ln)
        return self.rfile.read(ln) if ln else b""

    def _params(self) -> Dict[str, str]:
        q = urllib.parse.urlparse(self.path).query
        out = {k: v[0] for k, v in urllib.parse.parse_qs(q).items()}
        raw = self._read_body()
        if raw:
            raw = raw.decode()
            ctype = self.headers.get("Content-Type", "")
            if "json" in ctype:
                out.update(json.loads(raw))
            else:
                out.update({k: v[0] for k, v in urllib.parse.parse_qs(raw).items()})
        return out

    def _dispatch(self, method: str):
        path = urllib.parse.urlparse(self.path).path
        # observability spine: every request runs under a root span whose
        # trace id comes from the client's X-H2O3-Trace-Id header (minted
        # here when absent) and is echoed back by _send; child work — jobs,
        # candidates, batches, parses, munge ops — records into the same
        # trace. Assigned first thing, per request: the handler instance
        # persists across a keep-alive connection, so a stale id must never
        # leak into the next request's response (a 401/404 included).
        tid = (self.headers.get("X-H2O3-Trace-Id") or "")[:64]
        self._trace_id = tid or tracing.new_trace_id()
        token = getattr(self.server, "auth_token", None)
        if token:
            # bearer-token auth (the `-internal_security_conf` stance:
            # reject before any handler runs; /3/Cloud stays open so
            # clients can discover the cloud and fail with a clear 401)
            import hmac

            sent = self.headers.get("Authorization", "")
            ok = (hmac.compare_digest(sent, f"Bearer {token}")
                  or hmac.compare_digest(sent, f"Basic {token}"))
            if not ok and path not in ("/3/Cloud", "/3/Cloud/"):
                self._send(dict(__meta=dict(schema_type="H2OError"),
                                msg="unauthorized: missing or bad "
                                    "Authorization header",
                                http_status=401), 401)
                return
        for m, pat, name in self.ROUTES:
            if m != method:
                continue
            g = re.match(pat, path)
            if g:
                self._status = 200
                t0 = time.perf_counter()
                try:
                    Timeline.record("rest", f"{method} {path}",
                                    trace_id=self._trace_id)
                    with tracing.span(f"{method} {path}", kind="request",
                                      trace_id=self._trace_id,
                                      handler=name):
                        getattr(self, "h_" + name)(
                            *[urllib.parse.unquote(x) for x in g.groups()])
                except _PayloadTooLarge as e:
                    self._send(dict(__meta=dict(schema_type="H2OError"),
                                    msg=str(e), http_status=413), 413)
                except FileNotFoundError as e:
                    # missing server-side paths (ImportFiles, Models.bin,
                    # flows) are client errors, not server bugs
                    self._send(dict(__meta=dict(schema_type="H2OError"),
                                    msg=str(e), http_status=404), 404)
                except KeyError as e:
                    self._send(dict(__meta=dict(schema_type="H2OError"),
                                    msg=f"not found: {e}",
                                    http_status=404), 404)
                except (ValueError, TypeError) as e:
                    # client errors → 4xx (H2OErrorV3 with http_status)
                    self._send(dict(__meta=dict(schema_type="H2OError"),
                                    msg=str(e), http_status=400,
                                    exception_type=type(e).__name__), 400)
                except Exception as e:
                    # server bugs are 5xx, not blamed on the client
                    Log.err(f"REST {path}: {e}")
                    self._send(dict(__meta=dict(schema_type="H2OError"),
                                    msg=str(e), http_status=500,
                                    dev_msg=f"unhandled in h_{name}",
                                    exception_type=type(e).__name__), 500)
                finally:
                    _REQ_COUNT.inc(1, name, str(self._status))
                    _REQ_MS.observe((time.perf_counter() - t0) * 1e3, name)
                return
        self._send(dict(msg=f"no route for {method} {path}"), 404)

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def do_DELETE(self):
        self._dispatch("DELETE")

    # -- handlers ------------------------------------------------------------
    def h_flow(self):
        """`/flow/` — the built-in web UI (h2o-web's Flow analog)."""
        from .flow import FLOW_HTML

        body = FLOW_HTML.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/html; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def h_cloud(self):
        import h2o3_tpu
        from ..parallel import mesh

        try:
            c = mesh.cloud()
            size, healthy = c.size, True
        except Exception:
            size, healthy = 0, False
        self._send(dict(version=h2o3_tpu.__version__, cloud_name="h2o3_tpu",
                        cloud_size=size, cloud_healthy=healthy,
                        consensus=True, locked=True,
                        # store accounting (the reference's per-node
                        # free_mem/Cleaner bookkeeping, reported per cloud)
                        dkv=DKV.stats()))

    def h_about(self):
        import h2o3_tpu

        self._send(dict(entries=[dict(name="Build project version",
                                      value=h2o3_tpu.__version__)]))

    def h_import_files(self):
        # the internal parser, NOT h2o.import_file: the package-level surface
        # routes to an attached remote server, and a process acting as BOTH
        # server and client (notebook + local server) must not loop back
        from ..frame.parse import import_file as _parse_import

        p = self._params()
        fr = _parse_import(p["path"], pattern=p.get("pattern") or None)
        DKV.put(fr.key, fr)
        self._send(dict(destination_frames=[fr.key], fails=[], dels=[]))

    def h_parse_setup(self):
        p = self._params()
        paths = p.get("source_frames") or [p.get("path")]
        if isinstance(paths, str):
            paths = json.loads(paths) if paths.startswith("[") else [paths]
        from ..frame.parse import import_file

        fr = import_file(paths[0].strip('"'))
        self._send(dict(
            source_frames=paths,
            number_columns=fr.ncol,
            column_names=fr.names,
            column_types=[fr.vec(n).type for n in fr.names],
            separator=44,
        ))

    def h_parse(self):
        from ..frame.parse import import_file as _parse_import

        p = self._params()
        paths = p.get("source_frames")
        if isinstance(paths, str):
            paths = json.loads(paths) if paths.startswith("[") else [paths]
        # ParseSetup-style overrides (water/parser ParseSetupV3 fields):
        # separator/column_names/column_types ride the Parse request so
        # remote clients get the same parse control as in-process callers
        sep = p.get("separator") or None
        if isinstance(sep, str) and sep.isdigit():
            sep = chr(int(sep))                # upstream sends a byte value
        col_names = p.get("column_names")
        if isinstance(col_names, str):
            col_names = json.loads(col_names)
        col_types = p.get("column_types")
        if isinstance(col_types, str):
            col_types = json.loads(col_types)
        if isinstance(col_types, list):
            # ParseV3 sends types positionally; the parser wants name→type
            names_for_types = col_names
            if not names_for_types:
                probe = _parse_import(paths[0].strip('"'), sep=sep)
                names_for_types = probe.names
            col_types = dict(zip(names_for_types, col_types))
        fr = _parse_import(paths[0].strip('"'), sep=sep,
                           col_names=col_names, col_types=col_types)
        dest = p.get("destination_frame")
        if dest:
            fr.key = dest
        DKV.put(fr.key, fr)
        self._send(dict(job=dict(status="DONE", dest=dict(name=fr.key)),
                        destination_frame=dict(name=fr.key)))

    def h_frames_list(self):
        """`GET /3/Frames[?offset=&limit=]` — paginated like the reference's
        FramesHandler (water/api/FramesHandler list pagination)."""
        p = self._params()
        offset = max(0, int(p.get("offset", 0) or 0))
        limit = max(0, int(p.get("limit", 0) or 0))
        frames = [DKV.get(k) for k in DKV.keys(Frame)]
        total = len(frames)
        if offset:
            frames = frames[offset:]
        if limit:
            frames = frames[:limit]
        self._send(dict(total_frames=total, offset=offset,
                        frames=[dict(frame_id=dict(name=f.key), rows=f.nrow,
                                     columns=f.ncol) for f in frames]))

    def h_frame_get(self, key):
        """`GET /3/Frames/{id}[?row_offset=&row_count=]` — summary, plus a
        data page when row_count is given (FramesHandler.fetch paging)."""
        fr = DKV.get(key)
        if not isinstance(fr, Frame):
            raise KeyError(key)
        p = self._params()
        summ = _frame_summary(fr)
        if p.get("row_count") not in (None, ""):
            off = max(int(p.get("row_offset", 0)), 0)
            cnt = min(int(p["row_count"]), 10_000)   # bulk = DownloadDataset
            summ["row_offset"] = off
            summ["row_count"] = cnt
            for cmeta in summ["columns"]:
                v = fr.vec(cmeta["label"])
                if v.type == "enum":
                    dom = np.asarray((v.domain or []) + [None], dtype=object)
                    vals = dom[np.asarray(v.data[off:off + cnt], np.int64)]
                    cmeta["data"] = [None if x is None else str(x)
                                     for x in vals]
                elif v.type == "string":
                    vals = np.asarray(v.to_numpy(), dtype=object)[
                        off:off + cnt]
                    cmeta["data"] = [None if x is None else str(x)
                                     for x in vals]
                else:
                    a = v.numeric_np()[off:off + cnt]
                    cmeta["data"] = [None if np.isnan(x) else float(x)
                                     for x in a]
        self._send(dict(frames=[summ]))

    h_frame_summary = h_frame_get

    def h_frame_delete(self, key):
        DKV.remove(key)
        self._send(dict())

    @staticmethod
    def _flag(p, name) -> bool:
        """REST booleans arrive as strings — 'false'/'0' must be False."""
        v = p.get(name)
        if isinstance(v, str):
            return v.lower() in ("true", "t", "1")
        return bool(v)

    def h_frame_export(self, key):
        """/3/Frames/{id}/export — write a frame to a server-side path
        (water/api FramesHandler.export)."""
        import h2o3_tpu as h2o

        fr = DKV.get(key)
        if not isinstance(fr, Frame):
            raise KeyError(key)
        p = self._params()
        h2o.export_file(fr, p["path"], force=self._flag(p, "force"))
        self._send(dict(job=dict(status="DONE"), path=p["path"]))

    def h_model_save(self, model_id):
        """/99/Models.bin/{id} — persist a model artifact to a server-side
        directory (the reference's `h2o.save_model` → /99/Models.bin)."""
        import h2o3_tpu as h2o

        p = self._params()
        # DKV directly, NOT h2o.get_model: the package surface routes to an
        # attached remote connection (server+client in one process)
        m = DKV.get(model_id)
        if m is None:
            raise KeyError(model_id)
        path = h2o.save_model(m, p.get("dir") or ".",
                              force=self._flag(p, "force"))
        self._send(dict(path=path))

    def h_model_load(self):
        """/99/Models.bin — load a saved artifact. The offline scorer must
        NOT clobber a live model under the same id (every model route
        type-checks for H2OModel), so a taken id gets a _loaded suffix."""
        import h2o3_tpu as h2o

        p = self._params()
        src = p["dir"] if "dir" in p else p["path"]
        scorer = h2o.load_model(src)
        if self._flag(p, "delete_source"):
            # upload flow: the PostFile temp copy is spent once loaded —
            # keeping it would leak one zip per upload in the server tmpdir
            try:
                os.unlink(src)
            except OSError:
                pass
        mid = base = scorer.meta.get("model_id", "loaded_model")
        i = 0
        while DKV.get(mid) is not None:
            i += 1
            mid = f"{base}_loaded{i if i > 1 else ''}"
        DKV.put(mid, scorer)
        self._send(dict(models=[dict(model_id=dict(name=mid))]))

    def h_shutdown(self):
        """/3/Shutdown — stop the REST server (water/api ShutdownHandler)."""
        self._send(dict(result="shutting down"))
        import threading

        threading.Thread(target=self.server.shutdown, daemon=True).start()

    def h_builder_schema(self, algo):
        self._send(schemas.schema_for(algo))

    def h_train(self, algo):
        reg = schemas.algo_registry()
        if algo not in reg:
            raise KeyError(algo)
        p = self._params()
        train_key = p.pop("training_frame", None)
        valid_key = p.pop("validation_frame", None)
        y = p.pop("response_column", p.pop("y", None))
        x = p.pop("x", None)
        ignored = p.pop("ignored_columns", None)
        train = DKV.get(train_key) if train_key else None
        if train is None:
            raise ValueError(f"training_frame {train_key!r} not in DKV")
        valid = DKV.get(valid_key) if valid_key else None
        if isinstance(x, str):
            x = json.loads(x)
        if isinstance(ignored, str):
            ignored = json.loads(ignored)
        cls = reg[algo]
        known = {**cls._common_defaults, **cls._param_defaults}
        kwargs = {}
        for k, v in p.items():
            if k in known:
                if isinstance(v, str):
                    try:
                        v = json.loads(v)
                    except (ValueError, TypeError):
                        pass
                kwargs[k] = v
        if ignored:
            kwargs["ignored_columns"] = ignored
        est = cls(**kwargs)
        import uuid

        job = Job(dest=f"{algo}_rest_{uuid.uuid4().hex[:8]}",
                  description=f"{algo} train").start()
        job.trace_id = tracing.current_trace_id()
        job.result = None  # model key once DONE (the job's `dest` is stable)
        DKV.put(job.dest, job)
        # the estimator adopts THIS job, so /3/Jobs progress and
        # DELETE /3/Jobs/{id} cancellation act on the run itself
        est._external_job = job

        def run():
            from ..models.model_base import JobCancelled
            from ..parallel import mesh

            try:
                with tracing.attach(job.trace_id, name=f"job:{job.dest}",
                                    kind="job", algo=algo), \
                        mesh.training_guard():
                    est.train(x=x, y=y, training_frame=train,
                              validation_frame=valid)
                m = est.model
                DKV.put(m.model_id, m)
                job.result = m.model_id
                job.done()
            except JobCancelled:
                Log.info(f"train {algo}: cancelled")   # status already set
            except Exception as e:
                Log.err(f"train {algo}: {e}")
                job.status = "FAILED"
                job.warnings.append(str(e))
            finally:
                # leak canary: a FAILED/CANCELLED job that left its dest
                # model in the DKV surfaces in /3/Memory's leak report
                from ..runtime import memory_ledger

                memory_ledger.job_end(job.result or job.dest, job.status)

        threading.Thread(target=run, daemon=True).start()
        self._send(dict(job=dict(key=dict(name=job.dest), status=job.status)))

    # -- saved flows (h2o-web Flow notebooks: save/load named cell lists) ---
    @staticmethod
    def _flows_dir():
        d = os.environ.get("H2O3_FLOWS_DIR") or os.path.join(
            os.path.expanduser("~"), ".h2o3tpu_flows")
        os.makedirs(d, exist_ok=True)
        return d

    @staticmethod
    def _flow_path(name):
        # Distinct names must map to distinct files: substituting disallowed
        # characters would collide "my flow" with "my_flow" and silently
        # overwrite, so reject instead (400 via ValueError).
        if not name:
            raise ValueError("flow name required")
        if len(name) > 128 or re.search(r"[^A-Za-z0-9._-]", name):
            raise ValueError(
                "flow name must match [A-Za-z0-9._-]{1,128}: %r" % name)
        return os.path.join(_Handler._flows_dir(), name + ".flow.json")

    def h_flows_list(self):
        d = self._flows_dir()
        out = []
        for f in sorted(os.listdir(d)):
            if f.endswith(".flow.json"):
                out.append(dict(name=f[: -len(".flow.json")],
                                modified=os.path.getmtime(
                                    os.path.join(d, f))))
        self._send(dict(flows=out))

    def h_flow_save(self):
        p = self._params()
        name = p.get("name")
        cells = p.get("cells")
        if isinstance(cells, str):
            cells = json.loads(cells)
        if not isinstance(cells, list):
            raise ValueError("cells must be a list of {type, src}")
        path = self._flow_path(str(name or ""))
        with open(path, "w") as f:
            json.dump(dict(name=name, cells=cells), f)
        self._send(dict(name=name, saved=True, cells=len(cells)))

    def h_flow_load(self, name):
        path = self._flow_path(name)
        if not os.path.exists(path):
            raise KeyError(name)
        with open(path) as f:
            self._send(json.load(f))

    def h_flow_delete(self, name):
        path = self._flow_path(name)
        if not os.path.exists(path):
            raise KeyError(name)
        os.remove(path)
        self._send(dict(name=name, deleted=True))

    def h_tree(self):
        """`GET /3/Tree` — fetch one tree of a tree model (TreeV3 /
        `hex/tree/TreeHandler.java`): params model, tree_number,
        tree_class."""
        from ..tree_api import H2OTree

        p = self._params()
        mkey = p.get("model")
        m = DKV.get(mkey) if mkey else None
        if m is None:
            raise KeyError(f"model {mkey!r}")
        tree = H2OTree(m, int(p.get("tree_number", 0) or 0),
                       p.get("tree_class") or None)
        self._send(dict(
            model=dict(name=tree.model_id),
            tree_number=tree.tree_number,
            tree_class=tree.tree_class,
            root_node_id=tree.root_node_id,
            left_children=tree.left_children,
            right_children=tree.right_children,
            features=tree.features,
            thresholds=tree.thresholds,
            predictions=tree.predictions,
            nas=tree.nas,
            descriptions=tree.descriptions,
        ))

    def h_model_metrics_list(self):
        """`GET /3/ModelMetrics` — every stored model's metrics
        (ModelMetricsListSchemaV3 / water/api ModelMetricsHandler list)."""
        out = []
        for k in DKV.keys(H2OModel):
            m = DKV.get(k)
            for kind in ("training_metrics", "validation_metrics",
                         "cross_validation_metrics"):
                mm = getattr(m, kind, None)
                if mm is None:
                    continue
                d = {"model": dict(name=m.model_id), "kind": kind}
                for f in ("auc", "logloss", "rmse", "mse", "mean_residual_deviance"):
                    v = getattr(mm, f, None)
                    if v is not None:
                        try:
                            d[f] = float(v)
                        except (TypeError, ValueError):
                            pass
                out.append(d)
        self._send(dict(model_metrics=out))

    def h_typeahead(self):
        """`GET /99/Typeahead/files?src=...&limit=N` — filesystem path
        completion (water/api TypeaheadHandler)."""
        p = self._params()
        src = p.get("src", "") or ""
        limit = int(p.get("limit", 100) or 100)
        base = os.path.dirname(src) or "/"
        prefix = os.path.basename(src)
        matches = []
        try:
            for name in sorted(os.listdir(base)):
                if name.startswith(prefix):
                    full = os.path.join(base, name)
                    matches.append(full + ("/" if os.path.isdir(full) else ""))
                    if len(matches) >= limit:
                        break
        except OSError:
            pass
        self._send(dict(src=src, matches=matches, limit=limit))

    def h_network_test(self):
        """`GET /3/NetworkTest` — transport microbenchmark (water/api
        NetworkTestHandler analog). The reference measures node↔node RPC;
        the TPU framework's data plane is the host↔device link, so this
        times H2D+D2H round-trips per payload size (warm-up first — the
        first shape pays an XLA compile, which is not bandwidth). No
        collectives run here: a REST request reaches ONE rank, and a
        single-rank collective would hang the cloud (docs/distributed.md,
        concurrent-jobs section)."""
        import jax

        from ..runtime.nettest import run_network_test

        self._send(dict(nodes=jax.process_count(),
                        results=run_network_test()))

    def h_garbage_collect(self):
        """`POST /3/GarbageCollect` (water/api GarbageCollectHandler)."""
        import gc

        collected = gc.collect()
        self._send(dict(collected=collected, dkv=DKV.stats()))

    def h_water_meter(self, nodeidx):
        """`GET /3/WaterMeterCpuTicks/{node}` — per-cpu tick counters
        (water/util WaterMeterCpuTicks; Flow's CPU meter)."""
        ticks = []
        try:
            with open("/proc/stat") as f:
                for line in f:
                    if re.match(r"^cpu\d+ ", line):
                        parts = line.split()
                        user, nice, sys_, idle = (int(v) for v in parts[1:5])
                        ticks.append([user + nice, sys_, 0, idle])
        except OSError:
            pass
        self._send(dict(cpu_ticks=ticks))

    def h_models_list(self):
        models = [DKV.get(k) for k in DKV.keys(H2OModel)]
        self._send(dict(models=[_model_json(m) for m in models]))

    def h_model_get(self, key):
        from ..mojo import MojoScorer

        m = DKV.get(key)
        if isinstance(m, MojoScorer):
            # uploaded artifact: reduced schema from its stored metadata
            self._send(dict(models=[dict(
                model_id=dict(name=key), algo=m.algo,
                uploaded_artifact=True, kind=m.meta.get("kind"),
                response_column_name=m.y, output={})]))
            return
        if not isinstance(m, H2OModel):
            raise KeyError(key)
        self._send(dict(models=[_model_json(m)]))

    def h_model_delete(self, key):
        DKV.remove(key)
        # drop the model's compiled scorers too — cache hygiene on delete
        # (the identity check in ScorerCache already guarantees a re-created
        # model under this key can never hit the stale executable)
        from ..serving import peek_engine

        eng = peek_engine()
        if eng is not None:
            eng.cache.invalidate(key)
        self._send(dict())

    def h_predict(self, model_key, frame_key):
        from ..mojo import MojoScorer
        from ..serving import RejectedError, get_engine

        m = DKV.get(model_key)
        fr = DKV.get(frame_key)
        # uploaded/loaded artifacts (MojoScorer) serve predictions too —
        # that's the point of h2o.upload_model against a serving cluster
        if not isinstance(m, (H2OModel, MojoScorer)):
            raise KeyError(model_key)
        if not isinstance(fr, Frame):
            raise KeyError(frame_key)
        p = self._params()
        # upstream ModelMetricsHandler.predict options: SHAP contributions
        # and leaf indices ride the same route as plain predictions
        if self._flag(p, "predict_contributions"):
            kind, suffix = "contributions", "_contributions"
        elif self._flag(p, "leaf_node_assignment"):
            kind, suffix = "leaves", "_leaves"
        else:
            kind, suffix = "predict", ""
        # the serving path (docs/serving.md): admission → micro-batcher →
        # compiled-scorer cache. Concurrent requests for one model coalesce
        # into one device batch; repeats hit a warm executable.
        try:
            pred = get_engine().score(model_key, m, fr, output_kind=kind)
        except RejectedError as e:
            # backpressure, not failure: 429 + Retry-After so load
            # balancers and client retry loops back off instead of piling on
            retry = str(max(1, int(-(-e.retry_after_s // 1))))
            self._send(dict(__meta=dict(schema_type="H2OError"),
                            msg=str(e), http_status=429), 429,
                       headers={"Retry-After": retry})
            return
        # deterministic key: re-scoring the same (model, frame, kind)
        # OVERWRITES the previous prediction frame — the DKV must not
        # accumulate one leaked frame per repeat call (tested by the
        # DKV.keys() leak assertion in tests/test_rest_api.py)
        pred.key = f"prediction{suffix}_{model_key}_{frame_key}"
        DKV.put(pred.key, pred)
        self._send(dict(predictions_frame=dict(name=pred.key)))

    def h_serving_metrics(self):
        """`GET /3/Serving/metrics` — the scoring subsystem's counters +
        latency histograms (schema: schemas.serving_metrics_schema; also
        folded into /3/Profiler via runtime/profiler.serving_stats)."""
        from ..serving import peek_engine

        p = self._params()
        if self._flag(p, "schema"):
            self._send(schemas.serving_metrics_schema())
            return
        eng = peek_engine()
        body = (eng.snapshot() if eng is not None
                else dict(models={}, totals={}, cache=None, admission=None,
                          failover=None, config=None))
        self._send(dict(__meta=dict(schema_type=schemas.SERVING_SCHEMA_NAME),
                        **body))

    # -- fault injection (runtime/faults — docs/robustness.md) --------------
    def h_faults_get(self):
        """`GET /3/Faults` — armed fault points + fire counts, plus the
        shared retry-policy counters."""
        from ..runtime import profiler

        self._send(profiler.fault_stats())

    def h_faults_set(self):
        """`POST /3/Faults` — arm one fault point (the REST face of
        `faults.arm`): params point (required), error (io/conn/device/
        crash/none), rate, count, latency_ms, seed, lane, match (substring
        of the check detail — version-targeted faults). Chaos drills against a
        live serving cluster use this instead of a restart with
        H2O3_FAULT_* env vars."""
        from ..runtime import faults

        p = self._params()
        point = p.get("point")
        if not point:
            raise ValueError("point is required (e.g. serving.scorer)")
        out = faults.arm(
            str(point),
            error=str(p.get("error", "io")),
            rate=float(p.get("rate", 1.0) or 0.0),
            count=int(p["count"]) if p.get("count") not in (None, "")
            else None,
            latency_ms=float(p.get("latency_ms", 0.0) or 0.0),
            seed=int(p.get("seed", 0) or 0),
            lane=int(p["lane"]) if p.get("lane") not in (None, "")
            else None,
            match=str(p["match"]) if p.get("match") else None,
            after=int(p.get("after", 0) or 0))
        self._send(out)

    def h_faults_delete(self):
        """`DELETE /3/Faults[?point=]` — disarm one point, or all."""
        from ..runtime import faults

        p = self._params()
        point = p.get("point")
        if point:
            self._send(dict(disarmed=bool(faults.disarm(str(point))),
                            point=point))
        else:
            faults.reset()
            self._send(dict(disarmed=True, point=None))

    def h_ingest_metrics(self):
        """`GET /3/Ingest/metrics` — parse-pipeline throughput counters +
        per-phase timings (schema: schemas.ingest_metrics_schema; also
        folded into /3/Profiler via runtime/profiler.ingest_stats)."""
        from ..runtime import profiler

        p = self._params()
        if self._flag(p, "schema"):
            self._send(schemas.ingest_metrics_schema())
            return
        self._send(dict(__meta=dict(schema_type=schemas.INGEST_SCHEMA_NAME),
                        **profiler.ingest_stats()))

    def h_munge_metrics(self):
        """`GET /3/Munge/metrics` — munging-engine throughput counters +
        per-op stage timings (schema: schemas.munge_metrics_schema; also
        folded into /3/Profiler via runtime/profiler.munge_stats)."""
        from ..runtime import profiler

        p = self._params()
        if self._flag(p, "schema"):
            self._send(schemas.munge_metrics_schema())
            return
        self._send(dict(__meta=dict(schema_type=schemas.MUNGE_SCHEMA_NAME),
                        **profiler.munge_stats()))

    def h_training_metrics(self):
        """`GET /3/Training/metrics` — the multi-model training engine's
        scheduler occupancy, per-candidate timings, CV reuse counters and
        dataset-artifact cache stats (schema: schemas.training_metrics_
        schema; also folded into /3/Profiler via
        runtime/profiler.training_stats)."""
        from ..runtime import profiler

        p = self._params()
        if self._flag(p, "schema"):
            self._send(schemas.training_metrics_schema())
            return
        self._send(dict(__meta=dict(schema_type=schemas.TRAINING_SCHEMA_NAME),
                        **profiler.training_stats()))

    def h_serving_cache_clear(self):
        """`DELETE /3/Serving/cache[?model=key]` — evict compiled scorers
        (all, or one model's) so a hot-swapped artifact re-traces."""
        from ..serving import peek_engine

        p = self._params()
        eng = peek_engine()
        n = eng.cache.invalidate(p.get("model") or None) if eng else 0
        self._send(dict(invalidated=n))

    def h_model_metrics(self, model_key, frame_key):
        from ..mojo import MojoScorer

        m = DKV.get(model_key)
        fr = DKV.get(frame_key)
        if isinstance(m, MojoScorer):
            raise ValueError(
                f"{model_key!r} is an uploaded artifact (offline scorer): "
                "server-side metrics need a full model — run "
                "/3/Predictions and compute metrics from the actuals "
                "(h2o.make_metrics)")
        if not isinstance(m, H2OModel):
            raise KeyError(model_key)
        if not isinstance(fr, Frame):
            raise KeyError(frame_key)
        mm = m.model_performance(fr)
        self._send(dict(model_metrics=[dict(
            model=dict(name=model_key), frame=dict(name=frame_key),
            **(mm._ser() if mm else {}))]))

    @staticmethod
    def _job_json(j):
        return dict(key=dict(name=j.dest), status=j.status,
                    progress=j.progress, warnings=j.warnings,
                    dest=dict(name=getattr(j, "result", None) or j.dest))

    def h_jobs_list(self):
        jobs = [DKV.get(k) for k in DKV.keys(Job)]
        self._send(dict(jobs=[self._job_json(j) for j in jobs]))

    def h_job_get(self, key):
        j = DKV.get(key)
        if not isinstance(j, Job):
            raise KeyError(key)
        self._send(dict(jobs=[self._job_json(j)]))

    def h_rapids(self):
        p = self._params()
        # `rows` lets callers (e.g. Flow plot cells reading all hist bins)
        # ask for more than the 10-row preview; capped at 10k. Parsed BEFORE
        # evaluation so a malformed value cannot leak a computed frame into
        # DKV on the 400 path.
        rows = p.get("rows")
        rows = 10 if rows in (None, "") else min(max(0, int(rows)), 10_000)
        sess = RapidsSession(DKV)
        res = sess.execute(p["ast"])
        if isinstance(res, Frame):
            if not getattr(res, "key", None):
                res.key = f"rapids_{id(res)}"
            DKV.put(res.key, res)
            self._send(dict(key=dict(name=res.key),
                            **_frame_summary(res, rows=rows)))
        elif isinstance(res, (int, float)):
            self._send(dict(scalar=res))
        else:
            self._send(dict(string=str(res) if res is not None else None))

    def h_logs(self):
        self._send(dict(logs=Log.get_logs()))

    def h_timeline(self):
        """`GET /3/Timeline[?since=cursor&n=]` — the bounded event ring,
        plus recent span summaries. Every event carries a monotone `seq`;
        pass the returned `cursor` back as `since=` to tail
        incrementally."""
        p = self._params()
        try:
            since = p.get("since")
            since = int(since) if since not in (None, "") else None
            # n clamps to [1, 10000]: n=0 must not mean "the whole ring",
            # and with since= it must not return an empty page whose
            # cursor jumps past (and permanently loses) unread events
            n = min(max(int(p.get("n", 1000) or 1000), 1), 10_000)
        except ValueError as e:
            self._send(dict(__meta=dict(schema_type="H2OError"),
                            msg=f"bad since=/n= query param: {e}",
                            http_status=400), 400)
            return
        events, cursor = Timeline.tail(since, n=n)
        self._send(dict(events=events, cursor=cursor,
                        spans=tracing.summaries(min(n, 200))))

    def h_metrics(self):
        """`GET /3/Metrics` — the central registry in Prometheus text
        exposition format: every counter/gauge/histogram of every
        subsystem (serving, ingest, munge, training, retry, faults, REST,
        XLA compile/retrace) in one scrape. `?schema=1` returns the
        ObservabilityV3 field metadata as JSON instead (the sibling
        /3/*/metrics convention). `?format=json` returns the LOSSLESS
        family export (label tuples, raw histogram buckets) that fleet
        aggregators consume, and `?scope=fleet` answers for the WHOLE
        fleet: every registered peer scraped and merged (counters summed,
        histogram buckets summed, gauges per-replica, unreachable peers
        as explicit h2o3_fleet_peer_up 0 series — docs/observability.md
        "Fleet scope")."""
        p = self._params()
        if self._flag(p, "schema"):
            self._send(schemas.observability_schema())
            return
        if p.get("scope") == "fleet":
            from ..runtime import fleet

            self._send_raw(fleet.fleet_metrics_text().encode(),
                           "text/plain; version=0.0.4; charset=utf-8")
            return
        if p.get("format") == "json":
            self._send(registry.export_state())
            return
        self._send_raw(registry.prometheus_text().encode(),
                       "text/plain; version=0.0.4; charset=utf-8")

    def h_memory(self):
        """`GET /3/Memory[?schema=1]` — the memory ledger: per-owner
        host/device bytes, by-kind totals, high watermarks + top owners at
        peak, leak report, pressure vs budget, and the device probe with
        the ledger-vs-runtime reconciliation (`unaccounted`). The same
        numbers scrape as `h2o3_memory_*` at GET /3/Metrics and fold into
        /3/Profiler."""
        from ..runtime import memory_ledger

        if self._flag(self._params(), "schema"):
            self._send(schemas.memory_schema())
            return
        self._send(dict(__meta=dict(schema_type=schemas.MEMORY_SCHEMA_NAME),
                        **memory_ledger.snapshot()))

    def h_trace(self):
        """`GET /3/Trace[?trace_id=][&scope=fleet]` — recorded spans as
        Chrome-trace/Perfetto JSON (load at ui.perfetto.dev). Without
        trace_id, the whole span ring exports; with it, one correlated
        request tree. `scope=fleet` pulls every registered peer's export
        too and merges them into one timeline with a process track per
        replica (X-H2O3-Trace-Id already crosses the client, so a
        trace_id-scoped fleet pull is one workflow across processes)."""
        p = self._params()
        tid = p.get("trace_id") or None
        if p.get("scope") == "fleet":
            from ..runtime import fleet

            self._send(fleet.fleet_trace(tid))
            return
        self._send(tracing.export_chrome(tid))

    # -- fleet aggregation (runtime/fleet — docs/observability.md) ----------
    def h_supervisor_get(self):
        """`GET /3/Supervisor[?schema=1]` — the elastic training
        supervisor: state machine, last abort/resume/checkpoint, counters,
        resolved config (runtime/supervisor; docs/robustness.md
        'Recovery matrix')."""
        from ..runtime import supervisor

        p = self._params()
        if self._flag(p, "schema"):
            self._send(schemas.supervisor_schema())
            return
        self._send(dict(
            __meta=dict(schema_type=schemas.SUPERVISOR_SCHEMA_NAME),
            **supervisor.snapshot()))

    def h_fleet_get(self):
        """`GET /3/Fleet[?probe=0]` — the fleet fold: per-replica liveness
        + serving counters + predict p99, fleet-merged totals. Scrapes
        peers by default; `probe=0` reports registration state only."""
        from ..runtime import fleet

        p = self._params()
        probe = p.get("probe") not in ("0", "false", "no")
        self._send(dict(__meta=dict(schema_type="FleetV3"),
                        **fleet.snapshot(scrape=probe)))

    def h_fleet_set(self):
        """`POST /3/Fleet` — register one peer replica: params `name`,
        `url` (REST origin). Replicas self-register through this (the
        launcher hook, fleet.register_with)."""
        from ..runtime import fleet

        p = self._params()
        self._send(fleet.register_peer(str(p.get("name") or ""),
                                       str(p.get("url") or "")))

    def h_fleet_delete(self):
        """`DELETE /3/Fleet?name=` — unregister one peer."""
        from ..runtime import fleet

        p = self._params()
        name = p.get("name")
        if not name:
            raise ValueError("name is required")
        self._send(dict(removed=bool(fleet.remove_peer(str(name))),
                        name=name))

    # -- serving fleet router (serving/router.py — docs/serving.md) ---------
    def h_router_get(self):
        """`GET /3/Router[?probe=1]` — the RouterV3 document: replica ring
        (liveness/drain/inflight/pressure/p99), per-model versions +
        live/canary/shadow pointers + split, canary health windows, shed/
        failover/rollback counters, config. `probe=1` forces a fleet
        scrape first; the default reads cached ring state (the
        metrics-consistency walk hits `?probe=0` — no HTTP fan-out)."""
        from ..serving import get_router

        p = self._params()
        if self._flag(p, "schema"):
            self._send(schemas.router_schema())
            return
        probe = self._flag(p, "probe")
        self._send(dict(__meta=dict(schema_type=schemas.ROUTER_SCHEMA_NAME),
                        **get_router().snapshot(probe=probe)))

    def h_router_post(self):
        """`POST /3/Router` — rollout control, one `action` per call:

        * ``publish`` (model, version[, path]) — export the DKV model (or
          copy the mojo at `path`) into the registry, atomically;
        * ``warm`` (model, version[, frame]) — fan the artifact out to
          every replica's scorer cache before any traffic flips;
        * ``canary`` (model, version[, pct]) — split pct% of traffic;
        * ``promote`` (model, version) — atomic hot-swap to live;
        * ``rollback`` (model[, reason]) — abort the canary (no-op with
          no canary, still timeline-logged);
        * ``shadow`` (model[, version]) — mirror-only scoring (empty
          version stops shadowing);
        * ``retire`` (model, version)."""
        from ..serving import get_router

        p = self._params()
        action = str(p.get("action") or "")
        model = str(p.get("model") or "")
        version = str(p.get("version") or "")
        if not action or not model:
            raise ValueError("action and model are required")
        router = get_router()
        reg = router.registry
        if action == "publish":
            path = p.get("path") or None
            out = reg.publish(model, version,
                              model=None if path else DKV.get(model),
                              source_path=path)
        elif action == "warm":
            out = router.warm(model, version, frame=p.get("frame") or None)
        elif action == "canary":
            pct = float(p.get("pct", router.config.canary_pct) or 0.0)
            out = reg.set_canary(model, version, pct)
        elif action == "promote":
            out = reg.promote(model, version)
        elif action == "rollback":
            out = reg.rollback(model, reason=str(p.get("reason") or ""))
        elif action == "shadow":
            out = reg.set_shadow(model, version or None)
        elif action == "retire":
            out = reg.retire(model, version)
        else:
            raise ValueError(f"unknown action {action!r} (publish/warm/"
                             "canary/promote/rollback/shadow/retire)")
        self._send(dict(action=action, **out))

    def h_router_predict(self, model_key, frame_key):
        """`POST /3/Router/models/{m}/frames/{f}` — the fleet scoring
        entry point: version split + least-loaded dispatch + failover.
        Mirrors the chosen replica's /3/Predictions response; sheds with
        429 + Retry-After; replica 4xx/exhausted-5xx pass through with
        their original status."""
        import urllib.error

        from ..serving import RejectedError, get_router

        p = self._params()
        try:
            doc = get_router().route(model_key, frame_key, params=p,
                                     trace_id=getattr(self, "_trace_id",
                                                      None))
        except RejectedError as e:
            retry = str(max(1, int(-(-e.retry_after_s // 1))))
            self._send(dict(__meta=dict(schema_type="H2OError"),
                            msg=str(e), http_status=429), 429,
                       headers={"Retry-After": retry})
            return
        except urllib.error.HTTPError as e:
            # mirror the replica's verdict (its body was already drained)
            self._send(dict(__meta=dict(schema_type="H2OError"),
                            msg=f"replica error: {e}",
                            http_status=e.code), e.code)
            return
        self._send(doc)

    def h_serving_warm(self):
        """`POST /3/Serving/warm` — the replica side of the router's warm
        fan-out: load the mojo artifact at `path` into the DKV under
        `model` (the versioned key) and, when `frame` names a DKV frame,
        prime the compiled-scorer cache by scoring it through the engine.
        Returns the XLA trace delta of the priming score — the registry
        records it per replica and the warm-load pin asserts the LIVE
        first predict traces nothing new."""
        from ..mojo import load_model
        from ..runtime import phases
        from ..serving import get_engine

        p = self._params()
        path, model_key = p.get("path"), p.get("model")
        if not path or not model_key:
            raise ValueError("path and model are required")
        scorer = load_model(str(path))
        DKV.put(str(model_key), scorer)
        out = dict(model=str(model_key), loaded=True, primed=False)
        frame_key = p.get("frame")
        fr = DKV.get(str(frame_key)) if frame_key else None
        if isinstance(fr, Frame):
            before = phases.xla_counts()
            pred = get_engine().score(str(model_key), scorer, fr)
            after = phases.xla_counts()
            pred.key = f"warm_{model_key}_{frame_key}"
            DKV.put(pred.key, pred)
            out.update(primed=True, frame=str(frame_key),
                       traces=after.get("traces", 0)
                       - before.get("traces", 0))
        self._send(out)

    def h_profiler(self):
        from ..runtime import profiler

        self._send(dict(nodes=[dict(node="local",
                                    entries=profiler.profile(nsamples=2,
                                                             interval=0.01))],
                        serving=profiler.serving_stats(),
                        ingest=profiler.ingest_stats(),
                        munge=profiler.munge_stats(),
                        training=profiler.training_stats(),
                        faults=profiler.fault_stats(),
                        tree=profiler.tree_stats(),
                        est=profiler.est_stats(),
                        xla=profiler.xla_stats(),
                        tracing=profiler.tracing_stats(),
                        memory=profiler.memory_stats(),
                        fleet=profiler.fleet_stats(),
                        router=profiler.router_stats(),
                        qos=profiler.qos_stats(),
                        metrics=profiler.registry_stats()))

    def h_metadata_schemas(self):
        self._send(dict(schemas=schemas.all_schemas()
                        + [schemas.observability_schema(),
                           schemas.memory_schema(),
                           schemas.router_schema()]))

    # -- uploads (PostFileHandler) ------------------------------------------
    def h_post_file(self):
        """`POST /3/PostFile` — raw or multipart upload to a server-side
        temp file; the returned destination key is a path usable as
        `source_frames` in ParseSetup/Parse (PostFileHandler semantics)."""
        import tempfile

        q = urllib.parse.urlparse(self.path).query
        qs = {k: v[0] for k, v in urllib.parse.parse_qs(q).items()}
        body = self._read_body()
        ctype = self.headers.get("Content-Type", "")
        if "multipart/form-data" in ctype and b"\r\n\r\n" in body:
            # minimal multipart: split on the boundary FIRST so a body with
            # several parts yields only the first part's payload instead of
            # embedding the later parts' headers (RFC 2046: the boundary
            # parameter may be quoted and need not be the last parameter)
            bpart = ctype.split("boundary=")[-1].split(";")[0].strip()
            boundary = b"--" + bpart.strip('"').encode()
            for part in body.split(boundary):
                if b"\r\n\r\n" not in part:
                    continue  # preamble / trailing "--\r\n"
                payload = part.split(b"\r\n\r\n", 1)[1]
                if payload.endswith(b"\r\n"):
                    payload = payload[:-2]
                body = payload
                break
        name = qs.get("destination_frame") or "upload"
        suffix = os.path.splitext(name)[1] or ".csv"
        tmp = tempfile.NamedTemporaryFile(
            prefix="h2o3_upload_", suffix=suffix, delete=False)
        tmp.write(body)
        tmp.close()
        self._send(dict(destination_frame=tmp.name,
                        total_bytes=len(body)))

    # -- grid search (GridSearchHandler, /99/Grids*) ------------------------
    def h_grid_train(self, algo):
        reg = schemas.algo_registry()
        if algo not in reg:
            raise KeyError(algo)
        p = self._params()
        train_key = p.pop("training_frame", None)
        y = p.pop("response_column", p.pop("y", None))
        x = p.pop("x", None)
        if isinstance(x, str):
            x = json.loads(x)
        train = DKV.get(train_key) if train_key else None
        if train is None:
            raise ValueError(f"training_frame {train_key!r} not in DKV")
        hyper = p.pop("hyper_parameters", None)
        if hyper is None:
            raise ValueError("hyper_parameters is required")
        if isinstance(hyper, str):
            hyper = json.loads(hyper)
        criteria = p.pop("search_criteria", None)
        if isinstance(criteria, str):
            criteria = json.loads(criteria)
        grid_id = p.pop("grid_id", None)
        parallelism = int(p.pop("parallelism", 1) or 1)
        cls = reg[algo]
        known = {**cls._common_defaults, **cls._param_defaults}
        base = {}
        for k, v in p.items():
            if k in known:
                if isinstance(v, str):
                    try:
                        v = json.loads(v)
                    except (ValueError, TypeError):
                        pass
                base[k] = v
        from ..models.grid import H2OGridSearch

        gs = H2OGridSearch(cls(**base), hyper, grid_id=grid_id,
                           search_criteria=criteria,
                           parallelism=parallelism)
        import uuid

        job = Job(dest=f"grid_rest_{uuid.uuid4().hex[:8]}",
                  description=f"{algo} grid").start()
        job.trace_id = tracing.current_trace_id()
        job.result = gs.grid_id
        # the sweep's parent job: POST /3/Jobs/{id}/cancel on it skips
        # unstarted combos and cancels in-flight candidates at their next
        # scoring boundary (runtime/trainpool.py child jobs)
        gs._external_job = job
        DKV.put(job.dest, job)
        DKV.put(gs.grid_id, gs)

        def run():
            from ..parallel import mesh

            try:
                with tracing.attach(job.trace_id, name=f"job:{job.dest}",
                                    kind="job", algo=algo), \
                        mesh.training_guard():
                    gs.train(x=x, y=y, training_frame=train)
                if job.cancel_requested:
                    job.status = "CANCELLED"
                    job.end_time = time.time()
                else:
                    job.done()
            except Exception as e:
                Log.err(f"grid {algo}: {e}")
                job.status = "FAILED"
                job.warnings.append(str(e))

        threading.Thread(target=run, daemon=True).start()
        self._send(dict(job=dict(key=dict(name=job.dest), status=job.status),
                        grid_id=gs.grid_id))

    @staticmethod
    def _grid_model_ids(gs):
        # live entries are estimators; recovered entries carry the artifact
        # path of the already-built model (grid recovery_dir semantics)
        return [e.model.model_id if hasattr(e, "model") else e.model_id
                for e in gs.models]

    def _grid_json(self, gs):
        return dict(
            grid_id=dict(name=gs.grid_id),
            model_ids=[dict(name=i) for i in self._grid_model_ids(gs)],
            hyper_names=list(gs.hyper_params),
            failure_details=[f.get("error", "") for f in gs.failed],
        )

    def h_grids_list(self):
        from ..models.grid import H2OGridSearch

        grids = [DKV.get(k) for k in DKV.keys(H2OGridSearch)]
        self._send(dict(grids=[self._grid_json(g) for g in grids]))

    def h_grid_get(self, grid_id):
        from ..models.grid import H2OGridSearch

        gs = DKV.get(grid_id)
        if not isinstance(gs, H2OGridSearch):
            raise KeyError(grid_id)
        self._send(self._grid_json(gs))

    # -- AutoML (/99/AutoMLBuilder, /99/Leaderboards) -----------------------
    def h_automl_build(self):
        p = self._params()
        spec = p.get("input_spec") or {}
        if isinstance(spec, str):
            spec = json.loads(spec)
        train_key = (spec.get("training_frame")
                     or p.get("training_frame"))
        y = spec.get("response_column") or p.get("response_column") or p.get("y")
        train = DKV.get(train_key) if train_key else None
        if train is None:
            raise ValueError(f"training_frame {train_key!r} not in DKV")
        if not y:
            raise ValueError("response_column is required")
        build = p.get("build_control") or {}
        if isinstance(build, str):
            build = json.loads(build)
        from ..automl.automl import H2OAutoML

        # 0 is meaningful for both (nfolds=0 disables CV, seed=0 is a valid
        # seed) — only fall back to the default when the key is truly absent
        seed = p.get("seed", build.get("seed"))
        nfolds = p.get("nfolds", build.get("nfolds"))
        kw = dict(seed=-1 if seed is None else int(seed),
                  nfolds=5 if nfolds is None else int(nfolds),
                  project_name=p.get("project_name"))
        max_models = int(p.get("max_models", build.get("max_models", 0)) or 0)
        if max_models:
            kw["max_models"] = max_models
        parallelism = int(p.get("parallelism",
                                build.get("parallelism", 1)) or 1)
        if parallelism != 1:
            kw["parallelism"] = parallelism
        # an EXPLICIT 0 means unlimited (the ctor default is 3600) — only
        # an absent key keeps the default
        max_rt = p.get("max_runtime_secs", build.get("max_runtime_secs"))
        if max_rt is not None and str(max_rt) != "":
            kw["max_runtime_secs"] = float(max_rt)
        if p.get("sort_metric"):
            kw["sort_metric"] = str(p["sort_metric"])
        for lk in ("exclude_algos", "include_algos"):
            v = p.get(lk, build.get(lk))
            if isinstance(v, str) and v:
                v = json.loads(v)
            if v:
                kw[lk] = list(v)
        aml = H2OAutoML(**kw)
        import uuid

        job = Job(dest=f"automl_rest_{uuid.uuid4().hex[:8]}",
                  description="AutoML").start()
        job.trace_id = tracing.current_trace_id()
        job.result = aml.project_name
        DKV.put(job.dest, job)
        DKV.put(aml.project_name, aml)
        x = spec.get("x") or p.get("x")
        if isinstance(x, str):
            x = json.loads(x)

        def run():
            from ..parallel import mesh

            try:
                with tracing.attach(job.trace_id, name=f"job:{job.dest}",
                                    kind="job", algo="automl"), \
                        mesh.training_guard():
                    aml.train(x=x, y=y, training_frame=train)
                job.done()
            except Exception as e:
                Log.err(f"automl: {e}")
                job.status = "FAILED"
                job.warnings.append(str(e))

        threading.Thread(target=run, daemon=True).start()
        self._send(dict(job=dict(key=dict(name=job.dest), status=job.status),
                        automl_id=dict(name=aml.project_name)))

    def _leaderboard_json(self, aml):
        # the build runs on a worker thread: leaderboard is None until
        # train() populates it — polling clients get an empty board, not 500
        rows = ([{k: v for k, v in r.items() if not k.startswith("_")}
                 for r in aml.leaderboard.rows]
                if aml.leaderboard is not None else [])
        lbm = (aml.leaderboard.sort_metric
               if aml.leaderboard is not None else None)
        return dict(project_name=aml.project_name,
                    leaderboard=dict(rows=rows, sort_metric=lbm))

    def h_automl_get(self, project):
        from ..automl.automl import H2OAutoML

        aml = DKV.get(project)
        if not isinstance(aml, H2OAutoML):
            raise KeyError(project)
        out = self._leaderboard_json(aml)
        leader = getattr(aml, "leader", None)
        out.update(leader=(dict(name=leader.model.model_id)
                           if leader is not None else None),
                   event_log=aml.event_log.events)
        self._send(out)

    def h_leaderboard_get(self, project):
        from ..automl.automl import H2OAutoML

        aml = DKV.get(project)
        if not isinstance(aml, H2OAutoML):
            raise KeyError(project)
        self._send(self._leaderboard_json(aml))

    # -- grid recovery (RecoveryHandler: POST /3/Recovery) ------------------
    def h_recovery(self):
        import h2o3_tpu as h2o

        p = self._params()
        rdir = p.get("recovery_dir")
        if not rdir:
            raise ValueError("recovery_dir is required")
        gs = h2o.load_grid(rdir, grid_id=p.get("grid_id"))
        DKV.put(gs.grid_id, gs)
        self._send(dict(grid_id=dict(name=gs.grid_id),
                        model_ids=[dict(name=i)
                                   for i in self._grid_model_ids(gs)]))


    # -- round-4 route tier (VERDICT r03 #9) --------------------------------
    def h_validate_params(self, algo):
        """`POST /3/ModelBuilders/{algo}/parameters` — validate WITHOUT
        training (ModelBuilderHandler validate_parameters)."""
        reg = schemas.algo_registry()
        if algo not in reg:
            raise KeyError(algo)
        p = self._params()
        cls = reg[algo]
        known = {**cls._common_defaults, **cls._param_defaults}
        skip = {"training_frame", "validation_frame", "response_column",
                "x", "y", "ignored_columns"}
        messages = []
        kwargs = {}
        for k, v in p.items():
            if k in skip:
                continue
            if k not in known:
                messages.append(dict(field_name=k, message_type="ERRR",
                                     message=f"unknown parameter {k!r}"))
                continue
            if isinstance(v, str):
                try:
                    v = json.loads(v)
                except (ValueError, TypeError):
                    pass
            kwargs[k] = v
        if not messages:
            try:
                est = cls(**kwargs)
                if hasattr(est, "_check_params"):
                    est._check_params()
            except (ValueError, TypeError) as e:
                messages.append(dict(field_name="", message=str(e),
                                     message_type="ERRR"))
        self._send(dict(
            messages=messages,
            error_count=sum(m["message_type"] == "ERRR" for m in messages)))

    def _send_bytes(self, data: bytes, ctype: str, filename: str):
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Disposition",
                         f'attachment; filename="{filename}"')
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def h_model_mojo(self, model_id):
        """`GET /3/Models/{id}/mojo` — download the MOJO artifact zip
        (ModelsHandler.fetchMojo)."""
        import tempfile

        from .. import mojo as mojolib

        from ..mojo import MojoScorer

        m = DKV.get(model_id)
        if not isinstance(m, (H2OModel, MojoScorer)):
            raise KeyError(model_id)
        with tempfile.TemporaryDirectory(prefix="h2o3_mojo_") as d:
            path = mojolib.save_model(m, d, force=True)
            with open(path, "rb") as f:
                data = f.read()
        self._send_bytes(data, "application/zip", f"{model_id}.zip")

    def h_download_dataset(self):
        """`GET /3/DownloadDataset?frame_id=` — stream a frame as CSV."""
        p = self._params()
        key = p.get("frame_id")
        fr = DKV.get(key)
        if not isinstance(fr, Frame):
            raise KeyError(key)
        from ..frame.frame import frame_to_csv

        self._send_bytes(frame_to_csv(fr).encode(), "text/csv",
                         f"{key}.csv")

    def h_split_frame(self):
        """`POST /3/SplitFrame` — ratios → destination frames
        (hex/SplitFrame)."""
        p = self._params()
        fr = DKV.get(p.get("dataset"))
        if not isinstance(fr, Frame):
            raise KeyError(p.get("dataset"))
        ratios = p.get("ratios")
        if isinstance(ratios, str):
            ratios = json.loads(ratios)
        dests = p.get("destination_frames")
        if isinstance(dests, str):
            dests = json.loads(dests)
        seed = int(p.get("seed") if p.get("seed") not in (None, "") else -1)
        parts = fr.split_frame(list(ratios),
                               seed=None if seed == -1 else seed)
        keys = []
        for i, part in enumerate(parts):
            part.key = (dests[i] if dests and i < len(dests)
                        else f"{fr.key}_part{i}")
            DKV.put(part.key, part)
            keys.append(part.key)
        self._send(dict(job=dict(status="DONE"),
                        destination_frames=[dict(name=k) for k in keys]))

    def h_session_open(self):
        """`POST /4/sessions` — h2o-py opens one per connection
        (InitIDHandler)."""
        import uuid

        sid = "_sid" + uuid.uuid4().hex[:12]
        DKV.put(sid, dict(type="session"))
        self._send(dict(session_key=sid))

    def h_session_close(self, sid):
        DKV.remove(sid)
        self._send(dict(session_key=sid))

    def h_remove_all(self):
        """`DELETE /3/DKV[?retained_keys=[...]]` — h2o.remove_all
        (RemoveAllHandler `retained_keys`): clear the DKV, keeping any
        listed keys."""
        p = self._params()
        retained = p.get("retained_keys")
        if isinstance(retained, str):
            retained = json.loads(retained) if retained else []
        keep = set(retained or [])
        keys = DKV.keys()
        if not keep:
            n = len(keys)
            DKV.clear()
        else:
            n = 0
            for k in list(keys):
                if k not in keep:
                    DKV.remove(k)
                    n += 1
        self._send(dict(removed=n, retained=sorted(keep)))

    @staticmethod
    def _opt(p, k, cast, dflt):
        """Optional request param: cast when present, default otherwise."""
        v = p.get(k)
        return dflt if v in (None, "") else cast(v)

    @staticmethod
    def _opt_bool(p, k, dflt=False):
        v = p.get(k)
        if v in (None, ""):
            return dflt
        return str(v).lower() in ("1", "true", "yes")

    def h_create_frame(self):
        """`POST /3/CreateFrame` — server-side synthetic frame generator
        (water/api CreateFrameHandler → hex/createframe); the REST face of
        `h2o.create_frame`."""
        import h2o3_tpu as _pkg

        p = self._params()
        _f = lambda k, cast, dflt: self._opt(p, k, cast, dflt)  # noqa: E731
        _b = lambda k, dflt: self._opt_bool(p, k, dflt)         # noqa: E731

        fr = _pkg._create_frame_local(
            rows=_f("rows", int, 10000), cols=_f("cols", int, 10),
            randomize=_b("randomize", True),
            real_fraction=_f("real_fraction", float, None),
            categorical_fraction=_f("categorical_fraction", float, None),
            integer_fraction=_f("integer_fraction", float, None),
            binary_fraction=_f("binary_fraction", float, None),
            factors=_f("factors", int, 5),
            real_range=_f("real_range", float, 100.0),
            integer_range=_f("integer_range", int, 100),
            missing_fraction=_f("missing_fraction", float, 0.0),
            has_response=_b("has_response", False),
            response_factors=_f("response_factors", int, 2),
            seed=_f("seed", int, None),
            frame_id=p.get("dest") or p.get("frame_id") or None)
        self._send(dict(job=dict(status="DONE"),
                        destination_frame=dict(name=fr.key),
                        rows=fr.nrow, cols=fr.ncol))

    def h_interaction(self):
        """`POST /3/Interaction` — pairwise/combined factor-interaction
        columns (water/api InteractionHandler → hex/Interaction.java)."""
        import h2o3_tpu as _pkg

        p = self._params()
        fr = DKV.get(p.get("source_frame") or p.get("dataset"))
        if not isinstance(fr, Frame):
            raise KeyError(p.get("source_frame") or p.get("dataset"))
        factors = p.get("factor_columns") or p.get("factors") or "[]"
        if isinstance(factors, str):
            factors = json.loads(factors)
        out = _pkg._interaction_local(
            fr, factors,
            pairwise=self._opt_bool(p, "pairwise"),
            max_factors=int(p.get("max_factors", 100)),
            min_occurrence=int(p.get("min_occurrence", 1)),
            destination_frame=p.get("dest") or None)
        self._send(dict(job=dict(status="DONE"),
                        destination_frame=dict(name=out.key),
                        cols=out.ncol))

    def h_builders_list(self):
        """`GET /3/ModelBuilders` — every registered algorithm + its
        parameter schema (ModelBuildersHandler.list; h2o-py algo
        discovery)."""
        reg = schemas.algo_registry()
        self._send(dict(model_builders={
            algo: dict(algo=algo, visibility="Stable",
                       can_build=["Supervised" if getattr(
                           cls, "supervised", True) else "Unsupervised"])
            for algo, cls in sorted(reg.items())}))

    def h_job_cancel(self, key):
        """`POST /3/Jobs/{id}/cancel` — request cancellation; the training
        driver honors it at its next scoring boundary (water.Job.stop)."""
        job = DKV.get(key)
        if not isinstance(job, Job):
            raise KeyError(key)
        job.cancel()
        self._send(dict(job=dict(key=dict(name=key), status=job.status,
                                 cancel_requested=job.cancel_requested)))

    def h_frame_columns(self, key):
        """`GET /3/Frames/{id}/columns` — column labels/types page
        (FramesHandler.columns)."""
        fr = DKV.get(key)
        if not isinstance(fr, Frame):
            raise KeyError(key)
        p = self._params()
        off = int(p.get("column_offset", 0))
        cnt = int(p.get("column_count", -1))
        names = fr.names[off:] if cnt < 0 else fr.names[off:off + cnt]
        self._send(dict(
            frame_id=dict(name=key), num_columns=fr.ncol,
            column_offset=off,
            columns=[dict(label=n, type=fr.vec(n).type) for n in names]))

    def h_column_domain(self, key, col):
        """`GET /3/Frames/{id}/columns/{col}/domain` — categorical levels
        (FramesHandler.columnDomain)."""
        fr = DKV.get(key)
        if not isinstance(fr, Frame):
            raise KeyError(key)
        if col not in fr.names:
            raise KeyError(col)
        v = fr.vec(col)
        dom = list(v.domain or [])
        self._send(dict(domain=[dom], map=list(range(len(dom)))))

    def h_tabulate(self):
        """`POST /3/Tabulate` — co-occurrence counts + mean response of a
        predictor × response column pair, binned (hex/Tabulate.java; the
        Flow 'tabulate' cell)."""
        p = self._params()
        fr = DKV.get(p.get("dataset"))
        if not isinstance(fr, Frame):
            raise KeyError(p.get("dataset"))
        pred, resp = p.get("predictor"), p.get("response")
        for c in (pred, resp):
            if c not in fr.names:
                raise KeyError(c)
        nbins_p = int(p.get("nbins_predictor", 20))
        nbins_r = int(p.get("nbins_response", 10))
        w = (fr.vec(p["weight"]).numeric_np().astype(np.float64)
             if p.get("weight") and p["weight"] in fr.names
             else np.ones(fr.nrow))
        w = np.nan_to_num(w, nan=0.0)   # NA-weight rows drop out, not NaN-ify

        def _codes(col, nbins):
            v = fr.vec(col)
            if v.type == "enum":
                labels = list(v.domain or [])
                return np.asarray(v.data, np.int64), labels
            a = v.numeric_np().astype(np.float64)
            fin = a[~np.isnan(a)]
            lo, hi = (float(fin.min()), float(fin.max())) if fin.size else (0, 1)
            span = max(hi - lo, 1e-12)
            c = np.clip(((a - lo) / span * nbins).astype(np.int64),
                        0, nbins - 1)
            c = np.where(np.isnan(a), -1, c)
            edges = [lo + span * i / nbins for i in range(nbins)]
            return c, [f"[{e:.4g},{lo + span * (i + 1) / nbins:.4g})"
                       for i, e in enumerate(edges)]

        cp, lp = _codes(pred, nbins_p)
        cr, lr = _codes(resp, nbins_r)
        ok = (cp >= 0) & (cr >= 0)
        counts = np.zeros((len(lp), len(lr)))
        np.add.at(counts, (cp[ok], cr[ok]), w[ok])
        # numeric_np maps enum NA codes (-1) to NaN, so NA responses are
        # excluded below instead of dragging bin means negative
        rnum = fr.vec(resp).numeric_np().astype(np.float64)
        rsum = np.zeros(len(lp))
        rcnt = np.zeros(len(lp))
        okr = (cp >= 0) & ~np.isnan(rnum)
        np.add.at(rsum, cp[okr], (rnum * w)[okr])
        np.add.at(rcnt, cp[okr], w[okr])
        with np.errstate(invalid="ignore"):
            rmean = np.where(rcnt > 0, rsum / np.maximum(rcnt, 1e-300),
                             np.nan)
        self._send(dict(
            predictor=pred, response=resp,
            predictor_labels=lp, response_labels=lr,
            count_table=[[float(x) for x in row] for row in counts],
            response_table=[None if np.isnan(m) else float(m)
                            for m in rmean]))

    def h_jstack(self):
        """`GET /3/JStack` — stack-trace samples of every live thread
        (water/api JStackHandler → util/JStack)."""
        from ..runtime.profiler import stack_samples

        self._send(dict(traces=stack_samples()))

    def h_pdp(self):
        """`POST /3/PartialDependence` — partial-dependence tables for a
        model × frame (hex/PartialDependence.java; h2o-py partial_plot's
        REST face). Computed synchronously, stored under a key for
        GET /3/PartialDependence/{id}."""
        import uuid

        p = self._params()
        model = DKV.get(p.get("model_id"))
        fr = DKV.get(p.get("frame_id"))
        if model is None:
            raise KeyError(p.get("model_id"))
        if not isinstance(fr, Frame):
            raise KeyError(p.get("frame_id"))
        cols = p.get("cols")
        if isinstance(cols, str):
            cols = json.loads(cols)
        if isinstance(cols, str):       # a bare JSON string names ONE column
            cols = [cols]
        tables = model.partial_plot(
            fr, cols=cols, nbins=int(p.get("nbins", 20)),
            include_na=str(p.get("include_na", "")).lower()
            in ("1", "true"))

        def _cell(x):
            # np.float32 is not a `float` — go through float() so every
            # numeric NaN (any width) becomes JSON null, never a NaN token
            if isinstance(x, str) or x is None:
                return x
            xf = float(x)
            return None if np.isnan(xf) else xf

        out = [{c: [_cell(x) for x in t.vec(c).to_numpy()]
                for c in t.names} for t in tables]
        key = p.get("destination_key") or f"pdp_{uuid.uuid4().hex[:8]}"
        DKV.put(key, dict(type="pdp", cols=list(cols),
                          partial_dependence_data=out))
        self._send(dict(destination_key=dict(name=key), cols=list(cols),
                        partial_dependence_data=out))

    def h_pdp_get(self, key):
        obj = DKV.get(key)
        if not isinstance(obj, dict) or obj.get("type") != "pdp":
            raise KeyError(key)
        self._send(dict(destination_key=dict(name=key),
                        cols=obj["cols"],
                        partial_dependence_data=obj[
                            "partial_dependence_data"]))

    def h_w2v_synonyms(self):
        """`GET /3/Word2VecSynonyms?model=&word=&count=` —
        Word2VecHandler.findSynonyms."""
        p = self._params()
        model = DKV.get(p.get("model"))
        if model is None or not hasattr(model, "find_synonyms"):
            raise KeyError(p.get("model"))
        syn = model.find_synonyms(str(p.get("word", "")),
                                  int(p.get("count", 20)))
        self._send(dict(synonyms=list(syn.keys()),
                        scores=[float(v) for v in syn.values()]))

    def h_w2v_transform(self):
        """`POST /3/Word2VecTransform?model=&words_frame=&aggregate_method=`
        — Word2VecHandler.transform: embed a words column."""
        p = self._params()
        model = DKV.get(p.get("model"))
        fr = DKV.get(p.get("words_frame"))
        if model is None or not hasattr(model, "transform"):
            raise KeyError(p.get("model"))
        if not isinstance(fr, Frame):
            raise KeyError(p.get("words_frame"))
        out = model.transform(
            fr, aggregate_method=str(p.get("aggregate_method", "NONE")))
        DKV.put(out.key, out)
        self._send(dict(vectors_frame=dict(name=out.key),
                        cols=out.ncol, rows=out.nrow))

    def h_metadata_endpoints(self):
        """`GET /3/Metadata/endpoints` — the live route table
        (MetadataHandler.listRoutes)."""
        self._send(dict(routes=[
            dict(http_method=m, url_pattern=rx, handler=h)
            for m, rx, h in self.ROUTES]))

    def h_unlock_keys(self):
        """`POST /3/UnlockKeys` — upstream force-unlocks wedged key locks
        (UnlockKeysHandler). This DKV has no lock table by design (pytree
        values, functional updates), so there is never anything to unlock —
        the route answers honestly for client compatibility."""
        self._send(dict(unlocked=0,
                        note="DKV is lock-free by design; nothing to unlock"))

    def h_missing_inserter(self):
        """`POST /3/MissingInserter` — set a random fraction of a frame's
        cells to NA in place (water/api MissingInserterHandler); the REST
        face of `h2o.insert_missing_values`."""
        from .. import insert_missing_values as _imv

        p = self._params()
        fr = DKV.get(p.get("dataset"))
        if not isinstance(fr, Frame):
            raise KeyError(p.get("dataset"))
        seed = p.get("seed")
        _imv(fr, fraction=float(p.get("fraction", 0.1)),
             seed=None if seed in (None, "") else int(seed))
        self._send(dict(job=dict(status="DONE"),
                        frame_id=dict(name=fr.key)))

    def h_remove_key(self, key):
        DKV.remove(key)
        self._send(dict(key=dict(name=key)))

    def h_log_and_echo(self):
        p = self._params()
        msg = str(p.get("message", ""))
        Log.info(f"[LogAndEcho] {msg}")
        self._send(dict(message=msg))

    def h_capabilities(self):
        """`GET /3/Capabilities` — registered extensions
        (CapabilitiesHandler)."""
        self._send(dict(capabilities=[
            dict(name=n, capability_type="rest")
            for n in ("Algos", "AutoML", "Grid", "Rapids", "Flow",
                      "MOJO", "TargetEncoder", "RemoteClient")]))

    def h_ping(self):
        import time as _t

        self._send(dict(status="healthy", timestamp=_t.time()))

    def h_column_summary(self, key, col):
        """`GET /3/Frames/{id}/columns/{col}/summary` — per-column stats +
        histogram (FramesHandler.columnSummary)."""
        fr = DKV.get(key)
        if not isinstance(fr, Frame):
            raise KeyError(key)
        if col not in fr.names:
            raise KeyError(col)
        v = fr.vec(col)
        out = dict(label=col, type=v.type, nacnt=v.nacnt())
        if v.type in ("real", "int", "time"):
            a = v.numeric_np()
            fin = a[~np.isnan(a)]
            if fin.size:
                cnt, edges = np.histogram(fin, bins=20)
                srt = np.sort(fin)
                out.update(
                    mean=float(fin.mean()), sigma=float(fin.std()),
                    mins=[float(x) for x in srt[:5]],
                    maxs=[float(x) for x in srt[-5:][::-1]],
                    percentiles=[float(np.percentile(srt, q)) for q in
                                 (1, 10, 25, 50, 75, 90, 99)],
                    histogram_bins=[int(c) for c in cnt],
                    histogram_base=float(edges[0]),
                    histogram_stride=float(edges[1] - edges[0]))
        elif v.type == "enum":
            codes = np.asarray(v.data)
            cnts = np.bincount(codes[codes >= 0],
                               minlength=len(v.domain or []))
            out.update(domain=v.domain,
                       domain_cardinality=len(v.domain or []),
                       histogram_bins=[int(c) for c in cnts])
        self._send(dict(frames=[dict(frame_id=dict(name=key),
                                     columns=[out])]))


class H2OApiServer:
    """webserver-iface: owns the listening socket + handler thread.

    TLS: pass `ssl_certfile`/`ssl_keyfile` to serve HTTPS — the
    `-internal_security_conf` stance (water/network/SocketChannelFactory
    wraps the socket; here it's `ssl.SSLContext.wrap_socket`)."""

    def __init__(self, port: int = 54321, host: str = "127.0.0.1",
                 auth_token: Optional[str] = None,
                 ssl_certfile: Optional[str] = None,
                 ssl_keyfile: Optional[str] = None):
        self.httpd = ThreadingHTTPServer((host, port), _Handler)
        # opt-in bearer-token auth (the reference's -internal_security_conf
        # hash-login analog); None = open, like the reference's default
        self.httpd.auth_token = auth_token
        self.scheme = "http"
        if ssl_certfile:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(ssl_certfile, ssl_keyfile)
            # handshake in the per-request thread, NOT the accept loop: a
            # client that trickles its ClientHello must not block accept()
            # for everyone else (do_handshake_on_connect=False defers the
            # handshake to the first read, which runs in the handler
            # thread; the handler timeout below bounds it)
            self.httpd.socket = ctx.wrap_socket(
                self.httpd.socket, server_side=True,
                do_handshake_on_connect=False)
            self.scheme = "https"
        self.port = self.httpd.server_address[1]
        self.host = host
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "H2OApiServer":
        # a serving REST process always tracks XLA compiles/retraces — the
        # /3/Metrics retrace counters must not depend on bench env flags
        from ..runtime import phases

        phases.install_listener()
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="h2o3tpu-rest")
        self._thread.start()
        Log.info(f"REST server on {self.scheme}://{self.host}:{self.port}/3/")
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def start_server(port: int = 0, host: str = "127.0.0.1",
                 auth_token: Optional[str] = None,
                 ssl_certfile: Optional[str] = None,
                 ssl_keyfile: Optional[str] = None) -> H2OApiServer:
    return H2OApiServer(port=port, host=host, auth_token=auth_token,
                        ssl_certfile=ssl_certfile,
                        ssl_keyfile=ssl_keyfile).start()
