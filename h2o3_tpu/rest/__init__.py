"""REST API layer — the versioned `/3` endpoint surface.

Reference parity: `h2o-core/src/main/java/water/api/` (`RequestServer.java`
route table, `Handler.java`, `schemas3/**`) served by the pluggable Jetty
stack (`h2o-webserver-iface/`, `h2o-jetty-9/`). Here the clients are
in-process Python by default (zero-copy, no REST hop); this HTTP facade
exists for remote clients, Flow-style tooling, and parity with the
reference's wire surface.
"""

from .server import H2OApiServer, start_server  # noqa: F401
