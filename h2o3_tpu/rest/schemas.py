"""Schema registry — parameter metadata for every model builder.

Reference parity: `water/api/Schema.java` + `water/api/schemas3/*.java` and
the `/3/Metadata/schemas` endpoint that `h2o-bindings/bin/gen_python.py`
consumes to generate the client estimators. Here the single source of truth
is each estimator's `_param_defaults` (no codegen — SURVEY.md §2.6), and this
module renders the same metadata shape over REST.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Type


def _algo_registry() -> Dict[str, Type]:
    from .. import estimators as est

    reg = {}
    for name in est.__all__:
        cls = getattr(est, name)
        reg[cls.algo] = cls
    return reg


_registry_cache: Optional[Dict[str, Type]] = None


def algo_registry() -> Dict[str, Type]:
    global _registry_cache
    if _registry_cache is None:
        _registry_cache = _algo_registry()
    return _registry_cache


def _field_schema(name: str, default) -> Dict:
    t = type(default).__name__ if default is not None else "any"
    return dict(name=name, type=t, default_value=default, required=False)


def schema_for(algo: str) -> Dict:
    cls = algo_registry().get(algo)
    if cls is None:
        raise KeyError(algo)
    fields = [
        _field_schema(k, v)
        for k, v in {**cls._common_defaults, **cls._param_defaults}.items()
    ]
    return dict(
        algo=algo,
        name=f"{cls.__name__}V3",
        supervised=cls.supervised,
        parameters=fields,
    )


def all_schemas() -> List[Dict]:
    return [schema_for(a) for a in sorted(algo_registry())]


SERVING_SCHEMA_NAME = "ServingMetricsV3"
INGEST_SCHEMA_NAME = "IngestMetricsV3"
MUNGE_SCHEMA_NAME = "MungeMetricsV3"
TRAINING_SCHEMA_NAME = "TrainingMetricsV3"
OBSERVABILITY_SCHEMA_NAME = "ObservabilityV3"
MEMORY_SCHEMA_NAME = "MemoryV3"
ROUTER_SCHEMA_NAME = "RouterV3"
SUPERVISOR_SCHEMA_NAME = "SupervisorV3"

# the per-subsystem JSON metrics endpoints whose counter fields must be
# backed by central-registry metrics (metrics_registry.bind_rest_field);
# the metrics-consistency test walks these against GET /3/Metrics
METRICS_ENDPOINTS = {
    "serving": "/3/Serving/metrics",
    "ingest": "/3/Ingest/metrics",
    "munge": "/3/Munge/metrics",
    "training": "/3/Training/metrics",
    "memory": "/3/Memory",
    "fleet": "/3/Fleet?probe=0",
    "router": "/3/Router?probe=0",
    "supervisor": "/3/Supervisor",
}


def observability_schema() -> Dict:
    """Field metadata of the observability-spine surfaces
    (docs/observability.md mirrors this)."""
    fields = [
        ("GET /3/Metrics", "text/plain",
         "Prometheus text exposition (0.0.4) of the central metrics"
         " registry: every subsystem counter/gauge/histogram, HELP/TYPE"
         " lines, _total counter suffixes, _bucket/_sum/_count histogram"
         " series — the machine-scrapable surface"),
        ("GET /3/Trace?trace_id=", "TraceEventsJSON",
         "Chrome-trace/Perfetto JSON of recorded spans: request (root,"
         " trace id from the X-H2O3-Trace-Id header), job, candidate,"
         " batch, ingest and munge spans with retry/fault annotations"),
        ("GET /3/Timeline?since=&n=", "TimelineV3",
         "bounded event ring + recent span summaries; every event carries"
         " a monotone seq — pass the returned cursor back as since= for"
         " incremental tailing"),
        ("X-H2O3-Trace-Id", "header",
         "client-minted (or server-minted when absent) trace id,"
         " propagated into Jobs/candidates/batches and echoed on every"
         " response"),
        ("GET /3/Metrics?scope=fleet", "text/plain",
         "fleet-merged Prometheus exposition: every registered peer"
         " scraped (RetryPolicy) and merged — counters summed, histogram"
         " buckets summed (exact fleet percentiles), gauges per-replica"
         " under a replica label, unreachable peers as explicit"
         " h2o3_fleet_peer_up 0 series"),
        ("GET /3/Metrics?format=json", "JSON",
         "lossless registry export (labelnames, raw label tuples, raw"
         " histogram buckets + sum/min/max) — the payload fleet"
         " aggregators scrape and merge"),
        ("GET /3/Trace?scope=fleet", "TraceEventsJSON",
         "every replica's span export merged into one Chrome-trace"
         " timeline, one process_name track per replica"),
        ("GET/POST/DELETE /3/Fleet", "FleetV3",
         "peer registry + fleet fold: per-replica liveness, serving"
         " counters and predict p99, fleet-merged totals (the loadgen"
         " --fleet report source)"),
    ]
    return dict(
        name=OBSERVABILITY_SCHEMA_NAME,
        fields=[dict(name=n, type=t, help=h) for n, t, h in fields],
    )


def memory_schema() -> Dict:
    """Field metadata of the `GET /3/Memory` document (the memory
    ledger's observability schema — docs/observability.md "Memory
    accounting" mirrors this)."""
    fields = [
        ("totals", "MemoryTotals",
         "ledger-attributed bytes: host_bytes, device_bytes,"
         " leaked_bytes (dead owners whose buffers persist + DKV keys a"
         " failed job left behind), unaccounted_device_bytes (device"
         " probe minus attributed — the reconciliation remainder),"
         " owner_count"),
        ("owners", "list<OwnerBytes>",
         "per-owner breakdown (owner id, kind, host/device bytes, dead"
         " flag), largest first; owner ids follow the taxonomy"
         " dkv:<key> / dataset_cache:<fp>:<layer> / scorer:<model>:<kind>"
         " / ingest:<what>"),
        ("by_kind", "map<owner_kind, KindBytes>",
         "host/device bytes + owner count aggregated per owner kind"
         " (frame, model, dkv, dataset_cache, scorer, ingest) — the same"
         " aggregation scraped as h2o3_memory_bytes{owner_kind,space}"),
        ("watermarks", "MemoryWatermarks",
         "high watermark of host/device/total attributed bytes plus the"
         " top-3 owners captured at the combined peak (the bench-record"
         " memory embed reads this)"),
        ("pressure", "MemoryPressure",
         "pressure in [0,1]: max(host bytes vs H2O3_MEM_BUDGET_MB or"
         " MemTotal, device bytes vs device capacity); serving admission"
         " sheds at H2O3_SERVING_SHED_PRESSURE, dataset_cache evicts at"
         " H2O3_MEM_EVICT_PRESSURE, crossings of"
         " H2O3_MEM_PRESSURE_THRESHOLD are traced"),
        ("device", "DeviceProbe",
         "what the runtime actually holds: per-device memory_stats()"
         " where the backend reports them, else a live-buffer census"
         " (CPU fallback); the unattributed delta is reported as"
         " owner_kind=unaccounted — never silently absorbed"),
        ("leaks", "list<LeakReport>",
         "live leak report: owners whose referent died but whose buffers"
         " persist, and FAILED/CANCELLED jobs whose dest key still holds"
         " a model/frame; entries clear when the bytes are released"),
    ]
    return dict(
        name=MEMORY_SCHEMA_NAME,
        fields=[dict(name=n, type=t, help=h) for n, t, h in fields],
    )


def router_schema() -> Dict:
    """Field metadata of the `GET /3/Router` document (the serving fleet
    router's observability schema — docs/serving.md "Fleet serving"
    mirrors this)."""
    fields = [
        ("ring", "list<ReplicaState>",
         "the dispatch ring: per-replica name/url, up (from the fleet"
         " scrape, the h2o3_fleet_peer_up source), drained flag,"
         " router-local inflight count, consecutive_errors, scraped"
         " memory pressure and predict p99 — the least-loaded ordering"
         " ranks on (up, drained, inflight, pressure, p99)"),
        ("inflight", "int",
         "requests currently inside the router's fleet-wide token budget"
         " (sheds with 429 at H2O3_ROUTER_MAX_INFLIGHT)"),
        ("totals", "RouterTotals",
         "cumulative router counters: requests/errors (per-lane in the"
         " registry), shed (budget/pressure/no_replicas), retries,"
         " failovers, drains, rollbacks, warm_loads, shadow_* — every"
         " field is bind_rest_field-backed by an h2o3_router_* family"),
        ("models", "map<model, VersionTable>",
         "the registry fold: per-model live/canary/shadow pointers,"
         " canary_pct, and every version's state (published → warm →"
         " canary → live → retired/failed), artifact path and per-replica"
         " warm-load reports"),
        ("canary_health", "map<model, CanaryWindow>",
         "while a canary runs: per-lane (live vs canary) request/error"
         " counts and bucket p99 since the canary started — the inputs of"
         " the auto-rollback verdict"),
        ("config", "RouterConfig",
         "the H2O3_ROUTER_* knobs in effect (admission budget, drain"
         " thresholds, canary ratios, shadow compare depth)"),
    ]
    return dict(
        name=ROUTER_SCHEMA_NAME,
        fields=[dict(name=n, type=t, help=h) for n, t, h in fields],
    )


def supervisor_schema() -> Dict:
    """Field metadata of the `GET /3/Supervisor` document (the elastic
    training supervisor's observability schema — docs/robustness.md
    "Recovery matrix" mirrors this)."""
    fields = [
        ("state", "string",
         "supervisor state machine: idle (no supervised fit) / watching"
         " (a fit is inside its loop) / aborted (the last fence breach"
         " has not been superseded by a new fit)"),
        ("fit", "FitInfo",
         "the supervised fit in flight: tag (tree/estkmeans/estglm),"
         " run fingerprint, total steps, start timestamp"),
        ("heartbeat", "Heartbeat",
         "last liveness pulse from inside a supervised loop (chunk/"
         "segment/stream-block boundary): tag, step, timestamp — the"
         " background watcher reads its age"),
        ("last_abort", "AbortRecord",
         "most recent hung-collective abort: tag, detection latency (s),"
         " suspect ranks marked down, timestamp"),
        ("last_resume", "ResumeRecord",
         "most recent mid-fit checkpoint restore: tag, restored step,"
         " timestamp"),
        ("last_ckpt", "CkptRecord",
         "most recent committed snapshot: path, step, save wall (s)"),
        ("totals", "SupervisorTotals",
         "cumulative counters, each bind_rest_field-backed by an"
         " h2o3_supervisor_* family: aborts, resumes, ckpt_saves,"
         " ckpt_rejects (torn/wrong-fingerprint/incomplete-rank-set files"
         " skipped at restore), marked_down"),
        ("detect_ms", "histogram",
         "failure detection latency (ms): fence dispatch to abort"),
        ("config", "SupervisorConfig",
         "resolved knobs: ckpt_enabled (H2O3_CKPT), ckpt_dir"
         " (H2O3_CKPT_DIR), ckpt_trees (H2O3_CKPT_TREES),"
         " fence_deadline_s (H2O3_FENCE_DEADLINE_S), watcher (background"
         " failure watcher running)"),
    ]
    return dict(
        name=SUPERVISOR_SCHEMA_NAME,
        fields=[dict(name=n, type=t, help=h) for n, t, h in fields],
    )


def training_metrics_schema() -> Dict:
    """Field metadata of the `GET /3/Training/metrics` document (the
    multi-model training engine's observability schema — docs/training.md
    mirrors this)."""
    fields = [
        ("totals", "TrainingTotals",
         "cumulative pool counters since start (or reset): pools run,"
         " candidates submitted/completed/failed/cancelled/skipped,"
         " busy worker-seconds and pool wall-seconds"),
        ("cv", "CvReuseStats",
         "cross-validation fold accounting: reuse_folds (parent binned-"
         "matrix sliced per fold) vs rebin_folds (seed per-fold re-bin,"
         " H2O3_CV_REBIN=1 or non-tree builders)"),
        ("candidates", "list<CandidateStats>",
         "the most recent candidate builds: name/label/status/wall_s, the"
         " per-candidate phase split (host_prep/h2d/compile/trace/compute/"
         "metrics seconds, attributed via runtime/phases thread-local"
         " sinks) and bytes_h2d"),
        ("last_pool", "PoolStats",
         "the most recent sweep: parallelism (requested and effective —"
         " clouds that must serialize training degrade to 1), n_jobs,"
         " done/failed/cancelled/skipped, wall_s, busy_s and occupancy ="
         " busy/(wall×parallelism)"),
        ("cache", "DatasetCacheStats",
         "the dataset-artifact cache (models/dataset_cache.py): hits/"
         "misses per layer (matrix/bins/device), evictions, live entries,"
         " resident bytes, enabled flag"),
        ("totals.retried", "int",
         "candidate build attempts re-run after a TRANSIENT failure"
         " (runtime/retry classification; bounded by"
         " H2O3_TRAIN_CAND_RETRIES and the shared retry budget)"),
        ("totals.watchdog_cancelled", "int",
         "candidates cancelled by the per-candidate watchdog deadline"
         " (H2O3_TRAIN_CAND_DEADLINE_S)"),
        ("totals.resumed", "int",
         "sweep candidates satisfied from checkpoint records instead of"
         " retrained (grid recovery_dir auto-resume, AutoML"
         " checkpoint_dir — docs/robustness.md)"),
        ("totals.resumed_mid_fit", "int",
         "fits that restored a MID-FIT checkpoint and continued past"
         " tree/iteration 0 (runtime/supervisor, H2O3_CKPT_DIR —"
         " docs/robustness.md 'Recovery matrix')"),
        ("retry", "RetryStats",
         "shared retry-policy counters per policy (persist/client/"
         "trainpool): calls, retries, recovered, permanent_failures,"
         " deadline/attempts/budget exhaustions"),
        ("faults", "FaultStats",
         "armed fault-injection points + fire counts (runtime/faults;"
         " default off — GET/POST/DELETE /3/Faults)"),
        ("active", "boolean", "false until the first pooled sweep runs"),
    ]
    return dict(
        name=TRAINING_SCHEMA_NAME,
        fields=[dict(name=n, type=t, help=h) for n, t, h in fields],
    )


def munge_metrics_schema() -> Dict:
    """Field metadata of the `GET /3/Munge/metrics` document (the
    vectorized munging engine's observability schema — docs/munging.md
    mirrors this)."""
    fields = [
        ("totals", "MungeTotals",
         "cumulative ops/rows_in/rows_out/secs + derived rows_per_s over"
         " every munge op since start (or reset)"),
        ("ops", "map<op, MungeOpStats>",
         "per-op calls/errors/rows_in/rows_out/secs/rows_per_s + path"
         " counts (merge, group_by, pivot, table, apply_rows, moment,"
         " as_date, num_valid_substrings); a call that raised counts in"
         " errors with rows_out 0"),
        ("ops.*.paths", "map<string,int>",
         "how calls executed: vectorized (columnar kernels), fallback"
         " (exact per-row loop — after a failed vectorized attempt, or"
         " where vectorization doesn't apply: non-UTC moment, asDate on"
         " a non-string/enum column, 0-row apply), legacy"
         " (H2O3_MUNGE_LEGACY=1 seed path)"),
        ("last", "MungeOpStats",
         "the most recent op, or null before the first one"),
        ("last.rows_per_s", "double", "input rows / wall seconds"),
        ("last.stages", "map<string,double>",
         "per-stage seconds — merge books factorize / combine / match /"
         " assemble (same buckets runtime/phases records as munge_*)"),
        ("active", "boolean", "false until the first munge op happens"),
    ]
    return dict(
        name=MUNGE_SCHEMA_NAME,
        fields=[dict(name=n, type=t, help=h) for n, t, h in fields],
    )


def ingest_metrics_schema() -> Dict:
    """Field metadata of the `GET /3/Ingest/metrics` document (the chunked
    parse pipeline's observability schema — docs/ingest.md mirrors this)."""
    fields = [
        ("totals", "IngestTotals",
         "cumulative parses/rows/bytes/secs + derived rows_per_s,"
         " bytes_per_s over every parse since start (or reset)"),
        ("last", "IngestParseStats",
         "the most recent parse, or null before the first one"),
        ("last.rows_per_s", "double", "rows / wall seconds of that parse"),
        ("last.bytes_per_s", "double", "bytes / wall seconds of that parse"),
        ("last.n_chunks", "int",
         "byte chunks (or line blocks on the distributed path) tokenized"),
        ("last.n_threads", "int", "thread-pool workers used for phase 1"),
        ("last.native", "boolean",
         "true when the C++ per-chunk tokenizer handled the file"),
        ("last.distributed", "boolean",
         "true for the multi-process byte-range path"),
        ("last.phases", "map<string,double>",
         "per-stage seconds: setup / read / tokenize / coerce / intern /"
         " place (same buckets runtime/phases records as ingest_*)"),
        ("active", "boolean", "false until the first parse happens"),
    ]
    return dict(
        name=INGEST_SCHEMA_NAME,
        fields=[dict(name=n, type=t, help=h) for n, t, h in fields],
    )


def serving_metrics_schema() -> Dict:
    """Field metadata of the `GET /3/Serving/metrics` document (the serving
    subsystem's observability schema — docs/serving.md mirrors this)."""
    fields = [
        ("models", "map<model_key, ModelServingStats>",
         "per-model counters + histograms"),
        ("models.*.counters", "map<string,int>",
         "requests/rejections/errors, batches/batched_requests/batched_rows,"
         " compiles/cache_hits"),
        ("models.*.histograms.queue_wait_ms", "histogram",
         "request dwell in the micro-batch queue"),
        ("models.*.histograms.device_ms", "histogram",
         "scoring-call wall time per batch (includes compile on cold"
         " buckets)"),
        ("models.*.histograms.batch_size", "histogram",
         "requests coalesced per device batch"),
        ("models.*.counters (failover)", "map<string,int>",
         "scorer_faults (device/XLA errors), quarantines (poisoned"
         " executables evicted), scorer_rebuilds (rebuild-once succeeded),"
         " breaker_opens, fallback_scores (batches served by the"
         " compiled-CPU fallback)"),
        ("totals", "map<string,int>", "counters summed over all models"),
        ("cache", "CacheStats",
         "compiled-scorer LRU: capacity/size/hits/misses/evictions +"
         " per-entry warm row buckets"),
        ("admission", "AdmissionStats",
         "in-flight counts vs the global and per-model bounds"),
        ("failover", "FailoverStats",
         "per-(model, output_kind) circuit breakers (state/opens/time to"
         " half-open probe) + live CPU-fallback scorers"
         " (docs/robustness.md 'Serving failover')"),
        ("config", "ServingConfig", "the active knob values"),
    ]
    return dict(
        name=SERVING_SCHEMA_NAME,
        fields=[dict(name=n, type=t, help=h) for n, t, h in fields],
    )
