"""Schema registry — parameter metadata for every model builder.

Reference parity: `water/api/Schema.java` + `water/api/schemas3/*.java` and
the `/3/Metadata/schemas` endpoint that `h2o-bindings/bin/gen_python.py`
consumes to generate the client estimators. Here the single source of truth
is each estimator's `_param_defaults` (no codegen — SURVEY.md §2.6), and this
module renders the same metadata shape over REST.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Type


def _algo_registry() -> Dict[str, Type]:
    from .. import estimators as est

    reg = {}
    for name in est.__all__:
        cls = getattr(est, name)
        reg[cls.algo] = cls
    return reg


_registry_cache: Optional[Dict[str, Type]] = None


def algo_registry() -> Dict[str, Type]:
    global _registry_cache
    if _registry_cache is None:
        _registry_cache = _algo_registry()
    return _registry_cache


def _field_schema(name: str, default) -> Dict:
    t = type(default).__name__ if default is not None else "any"
    return dict(name=name, type=t, default_value=default, required=False)


def schema_for(algo: str) -> Dict:
    cls = algo_registry().get(algo)
    if cls is None:
        raise KeyError(algo)
    fields = [
        _field_schema(k, v)
        for k, v in {**cls._common_defaults, **cls._param_defaults}.items()
    ]
    return dict(
        algo=algo,
        name=f"{cls.__name__}V3",
        supervised=cls.supervised,
        parameters=fields,
    )


def all_schemas() -> List[Dict]:
    return [schema_for(a) for a in sorted(algo_registry())]
